"""Postmortem engine (obs/clock.py + obs/analyze.py + `tpujob why`).

Tentpole coverage for the cross-host postmortem PR:

- the heartbeat-matching clock-offset estimator: synthetic skewed hosts
  recover their offset/drift; jittered and dropped heartbeats are
  tolerated; the merged two-host trace orders rendezvous-join spans
  causally with skew residual under one heartbeat interval (the
  acceptance criterion);
- every detector rule firing on a crafted timeline — and NOT firing on
  a healthy one;
- the satellites: metric-series retirement bounds the registry under
  job churn, span ring/flush spec knobs thread env → recorder,
  histogram exemplars survive exposition round trips into `tpujob top`
  and the `why` report, top sort/filter helpers;
- the bench_smoke lane pin: analysis is OFFLINE-only (zero span records
  emitted by a whole run-plus-analysis with tracing disabled) and
  `tpujob why` on a healthy run reports zero findings.
"""

from __future__ import annotations

import json
import time

import pytest

from pytorch_operator_tpu import obs
from pytorch_operator_tpu.controller.store import key_to_fs
from pytorch_operator_tpu.obs import analyze as obs_analyze
from pytorch_operator_tpu.obs import clock as obs_clock
from pytorch_operator_tpu.obs import trace as obs_trace
from pytorch_operator_tpu.obs.clock import (
    ClockLog,
    estimate_job_offsets,
    estimate_offset,
    job_clock_log,
    load_observations,
    offsets_for_trace_files,
)

KEY = "default/pm"


# ---- artifact builders (the recorded surfaces `why` reads) ----


def _write_status(state, key, replica, recs) -> None:
    d = state / "status" / key_to_fs(key)
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"{replica}.jsonl", "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _beats(t0, n, interval, step0=1, step_time_ms=10.0, **extra):
    return [
        {
            "event": "progress",
            "ts": t0 + i * interval,
            "step": step0 + i,
            "steps_per_sec": 1000.0 / step_time_ms,
            "step_time_ms": step_time_ms,
            **extra,
        }
        for i in range(n)
    ]


def _write_events(state, key, evs) -> None:
    d = state / "events"
    d.mkdir(parents=True, exist_ok=True)
    with open(d / (key_to_fs(key) + ".events.jsonl"), "a") as f:
        for ts, etype, reason, msg in evs:
            f.write(
                json.dumps(
                    {
                        "timestamp": ts,
                        "type": etype,
                        "reason": reason,
                        "message": msg,
                        "count": 1,
                    }
                )
                + "\n"
            )


def _findings(state, key, window_s=None):
    report = obs_analyze.analyze(state, key, window_s=window_s)
    return report, [f["rule"] for f in report["findings"]]


# ---- clock-offset estimator ----


class TestClockEstimator:
    def test_constant_skew_recovered_exactly(self):
        # Worker clock 5s behind the supervisor, zero delay jitter.
        pairs = [(100.0 + i, 105.0 + i) for i in range(10)]
        est = estimate_offset(pairs)
        assert est.offset_s == pytest.approx(5.0, abs=1e-9)
        assert abs(est.drift_ppm) < 1.0
        assert est.residual_s < 1e-9

    def test_jittered_delays_tolerated(self):
        # Deterministic poll jitter in [0, 90ms]; true offset -3.2s.
        pairs = [
            (200.0 + 0.5 * i, 200.0 + 0.5 * i - 3.2 + ((i * 37) % 10) / 111.0)
            for i in range(40)
        ]
        est = estimate_offset(pairs)
        # The estimate absorbs at most ~the delay band, far under the
        # 0.5s heartbeat interval (the acceptance bound).
        assert abs(est.offset_s - (-3.2)) < 0.1
        assert est.residual_s < 0.1
        assert est.n == 40

    def test_drift_recovered(self):
        # 200 ppm rate error over a 1000s window + small jitter: the
        # drift-aware correction stays tight at BOTH ends of the window.
        drift = 200e-6
        pairs = [
            (s, s + 1.0 + drift * s + ((i * 13) % 7) / 700.0)
            for i, s in enumerate(range(0, 1000, 10))
        ]
        est = estimate_offset(pairs)
        assert 100.0 < est.drift_ppm < 300.0
        for s in (0.0, 500.0, 1000.0):
            true = 1.0 + drift * s
            assert abs(est.offset_at(s) - true) < 0.05

    def test_dropped_heartbeats_tolerated(self):
        # Keep only every third beat (drop_heartbeat-style gaps).
        pairs = [
            (100.0 + i, 100.0 + i + 2.5 + ((i * 29) % 5) / 200.0)
            for i in range(60)
            if i % 3 == 0
        ]
        est = estimate_offset(pairs)
        assert abs(est.offset_s - 2.5) < 0.05

    def test_no_pairs_is_none_and_few_pairs_no_drift(self):
        assert estimate_offset([]) is None
        est = estimate_offset([(1.0, 2.0), (2.0, 3.1)])
        assert est.drift_ppm == 0.0
        assert est.offset_s == pytest.approx(1.05, abs=0.06)

    def test_implausible_drift_collapses_to_pure_offset(self):
        # A short (1s) window turns delay jitter into a huge apparent
        # slope; the credibility clamp must zero it instead of
        # extrapolating garbage beyond the window.
        pairs = [
            (100.0 + i * 0.1, 100.0 + i * 0.1 + 1.0 + ((i * 7) % 3) / 50.0)
            for i in range(10)
        ]
        est = estimate_offset(pairs)
        assert est.drift_ppm == 0.0
        assert abs(est.offset_s - 1.0) < 0.05

    def test_log_roundtrip_and_rotation(self, tmp_path):
        path = job_clock_log(tmp_path, KEY)
        log = ClockLog(path, max_bytes=600)
        for i in range(20):
            log.observe("worker-0", 100.0 + i, 101.0 + i)
        obs_by_rep = load_observations(path)
        # The ring rotated (cap ~600B, ~85B/record) yet old + new
        # generations both load; newest pair present.
        assert path.with_suffix(".jsonl.1").exists()
        pairs = obs_by_rep["worker-0"]
        assert (119.0, 120.0) in pairs
        ests = estimate_job_offsets(tmp_path, KEY)
        assert ests["worker-0"].offset_s == pytest.approx(1.0, abs=1e-6)

    def test_supervisor_records_observations_with_priming(self, tmp_path):
        """First sight of a replica primes the dedup (a daemon restart
        must not pair a stale beat with a fresh observe time); the next
        beat is logged with a real observe timestamp."""
        from pytorch_operator_tpu.controller import FakeRunner
        from pytorch_operator_tpu.controller.supervisor import Supervisor

        sup = Supervisor(state_dir=tmp_path / "state", runner=FakeRunner())
        try:
            d = tmp_path / "state" / "status" / key_to_fs(KEY)
            _write_status(tmp_path / "state", KEY, "master-0",
                          _beats(100.0, 1, 0.5))
            sup._progress.poll(d)
            sup._record_clock_observations(KEY, d)
            assert load_observations(job_clock_log(tmp_path / "state", KEY)) == {}
            _write_status(tmp_path / "state", KEY, "master-0",
                          _beats(100.5, 1, 0.5, step0=2))
            sup._progress.poll(d)
            sup._record_clock_observations(KEY, d)
            got = load_observations(job_clock_log(tmp_path / "state", KEY))
            assert [s for s, _ in got["master-0"]] == [100.5]
            # Re-polling the same beat adds nothing (once per beat).
            sup._progress.poll(d)
            sup._record_clock_observations(KEY, d)
            assert len(load_observations(
                job_clock_log(tmp_path / "state", KEY))["master-0"]) == 1
        finally:
            sup.shutdown()


class TestTwoHostSkewMerge:
    """The acceptance e2e: a two-host synthetic-skew trace merge orders
    the rendezvous-join spans causally, skew residual under one
    heartbeat interval."""

    INTERVAL = 0.5
    SKEW = 2.0  # worker wall clock 2s BEHIND the supervisor/master host

    def _seed(self, tmp_path):
        state = tmp_path / "state"
        key = "default/skew"
        log = ClockLog(job_clock_log(state, key))
        for i in range(20):
            true = 100.0 + i * self.INTERVAL
            # Supervisor observes each beat a jittery-but-small delay
            # after the true send instant; the worker STAMPS its beat
            # on its own (skewed) clock.
            log.observe("worker-0", true - self.SKEW,
                        true + ((i * 37) % 10) / 150.0)
            log.observe("master-0", true, true + ((i * 23) % 10) / 150.0)
        trace_dir = state / "trace" / key_to_fs(key)
        rec_m = obs_trace.SpanRecorder(trace_dir, "master-0")
        # True order: the coordinator's join opens at t=100.0, the
        # worker joins at t=100.5 — but the worker's skewed clock
        # records 98.5, which naively merges FIRST.
        rec_m.emit("rendezvous_join", "rendezvous", 100.0, 0.2, src="master-0")
        rec_m.close()
        rec_w = obs_trace.SpanRecorder(trace_dir, "worker-0")
        rec_w.emit("rendezvous_join", "rendezvous", 100.5 - self.SKEW, 0.2,
                   src="worker-0")
        rec_w.close()
        return state, key, trace_dir

    def _joins(self, doc):
        return [
            e for e in doc["traceEvents"]
            if e.get("ph") == "X" and e["name"] == "rendezvous_join"
        ]

    def test_naive_merge_inverts_causality(self, tmp_path):
        state, key, trace_dir = self._seed(tmp_path)
        doc = obs_trace.merge_trace_files(obs_trace.span_files(trace_dir))
        assert [j["args"]["src"] for j in self._joins(doc)] == [
            "worker-0", "master-0"
        ]

    def test_estimated_offsets_restore_causal_order(self, tmp_path):
        state, key, trace_dir = self._seed(tmp_path)
        ests = estimate_job_offsets(state, key)
        # The worker's skew is recovered within the heartbeat interval.
        assert abs(ests["worker-0"].offset_s - self.SKEW) < self.INTERVAL
        assert ests["worker-0"].residual_s < self.INTERVAL
        paths = obs_trace.span_files(trace_dir)
        offsets = offsets_for_trace_files(paths, ests)
        doc = obs_trace.merge_trace_files(paths, clock_offsets=offsets)
        joins = self._joins(doc)
        assert [j["args"]["src"] for j in joins] == ["master-0", "worker-0"]
        # Residual bound on the corrected timestamp itself.
        worker_ts = next(
            j["ts"] for j in joins if j["args"]["src"] == "worker-0"
        )
        assert abs(worker_ts / 1e6 - 100.5) < self.INTERVAL
        # The merged doc is self-describing about the applied fix.
        corr = [
            m for m in doc["traceEvents"]
            if m.get("ph") == "M" and m.get("name") == "clock_sync_correction"
        ]
        assert corr and any(
            "worker-0" in m["args"]["file"] for m in corr
        )

    def test_trace_cli_applies_corrections(self, tmp_path, capsys):
        from pytorch_operator_tpu.client.cli import main

        state, key, trace_dir = self._seed(tmp_path)
        (state / "jobs").mkdir(parents=True, exist_ok=True)
        out = tmp_path / "t.json"
        assert main(
            ["--state-dir", str(state), "trace", "skew", "--out", str(out)]
        ) == 0
        assert "clock_sync" in capsys.readouterr().err
        doc = json.loads(out.read_text())
        assert [j["args"]["src"] for j in self._joins(doc)] == [
            "master-0", "worker-0"
        ]
        # --no-clock-sync keeps raw per-host timestamps.
        assert main(
            ["--state-dir", str(state), "trace", "skew", "--out", str(out),
             "--no-clock-sync"]
        ) == 0
        doc = json.loads(out.read_text())
        assert [j["args"]["src"] for j in self._joins(doc)] == [
            "worker-0", "master-0"
        ]


# ---- detector rules ----


class TestDetectors:
    def test_healthy_timeline_has_no_findings(self, tmp_path):
        state = tmp_path / "state"
        _write_status(state, KEY, "master-0", _beats(100.0, 20, 0.5))
        _write_status(
            state, KEY, "master-0",
            [{"event": "checkpoint_committed", "ts": 100.0 + s * 0.5,
              "step": s, "commit_ms": 5.0, "queue_depth": 0}
             for s in range(2, 21, 2)],
        )
        report, rules = _findings(state, KEY)
        assert rules == []
        assert report["replicas"]["master-0"]["beats"] == 20

    def test_step_time_regression_fires_with_evidence(self, tmp_path):
        state = tmp_path / "state"
        recs = _beats(100.0, 12, 0.5, step_time_ms=10.0)
        recs += _beats(106.0, 4, 0.5, step0=13, step_time_ms=40.0)
        _write_status(state, KEY, "master-0", recs)
        report, rules = _findings(state, KEY)
        assert "step_time_regression" in rules
        f = next(
            f for f in report["findings"]
            if f["rule"] == "step_time_regression"
        )
        assert f["metrics"]["recent_ms"] == pytest.approx(40.0)
        assert f["metrics"]["baseline_ms"] == pytest.approx(10.0)
        # Evidence cites the worst recent sample.
        ev = f["evidence"][0]
        assert ev["source"] == "status" and ev["step_time_ms"] == 40.0

    def test_window_bounds_the_regression_comparison(self, tmp_path):
        state = tmp_path / "state"
        recs = _beats(100.0, 12, 0.5, step_time_ms=10.0)
        recs += _beats(106.0, 4, 0.5, step0=13, step_time_ms=40.0)
        _write_status(state, KEY, "master-0", recs)
        # A window covering EVERYTHING leaves no baseline: no finding.
        _, rules = _findings(state, KEY, window_s=1000.0)
        assert "step_time_regression" not in rules
        # A 2s window isolates the slow tail against the earlier base.
        _, rules = _findings(state, KEY, window_s=2.0)
        assert "step_time_regression" in rules

    def test_feed_stall_dominance_fires(self, tmp_path):
        state = tmp_path / "state"
        _write_status(
            state, KEY, "master-0",
            _beats(100.0, 8, 0.5, step_time_ms=20.0, feed_stall_ms=15.0),
        )
        report, rules = _findings(state, KEY)
        assert rules == ["feed_stall_dominance"]
        f = report["findings"][0]
        assert f["metrics"]["share"] == pytest.approx(0.75)

    def test_checkpoint_lag_and_queue_growth_fire(self, tmp_path):
        state = tmp_path / "state"
        _write_status(state, KEY, "master-0", _beats(100.0, 30, 0.2))
        _write_status(
            state, KEY, "master-0",
            [{"event": "checkpoint_committed", "ts": 100.0 + i,
              "step": 2 + 2 * i, "commit_ms": 900.0, "queue_depth": 1 + i}
             for i in range(4)],
        )
        report, rules = _findings(state, KEY)
        assert rules.count("checkpoint_lag") == 2
        lag = next(
            f for f in report["findings"]
            if "trail" in f["summary"]
        )
        # Last trained step 30, last committed 8, cadence 2.
        assert lag["metrics"]["lag_steps"] == pytest.approx(22.0)
        assert lag["metrics"]["cadence_steps"] == pytest.approx(2.0)

    def test_heartbeat_silence_names_victim_before_kill(self, tmp_path):
        state = tmp_path / "state"
        _write_status(state, KEY, "master-0", _beats(100.0, 3, 0.5))
        _write_events(
            state, KEY,
            [(103.5, "Warning", "TPUJobHung",
              "no heartbeat for 2.5s; killing the hung world.")],
        )
        report, rules = _findings(state, KEY)
        assert "heartbeat_silence" in rules
        f = next(
            f for f in report["findings"] if f["rule"] == "heartbeat_silence"
        )
        assert f["severity"] == "critical"
        assert "master-0" in f["summary"]
        # Acceptance: the evidence records are timestamped BEFORE the
        # deadline kill.
        kill_ts = next(
            e["ts"] for e in f["evidence"] if e["source"] == "event"
        )
        for e in f["evidence"]:
            if e["source"] != "event":
                assert e["ts"] < kill_ts
        assert f["metrics"]["silence_s"] == pytest.approx(2.5)

    def test_partial_silence_without_kill(self, tmp_path):
        state = tmp_path / "state"
        _write_status(state, KEY, "worker-0", _beats(100.0, 21, 0.5))
        _write_status(state, KEY, "master-0", _beats(100.0, 4, 0.5))
        report, rules = _findings(state, KEY)
        assert "heartbeat_silence" in rules
        f = next(
            f for f in report["findings"] if f["rule"] == "heartbeat_silence"
        )
        assert "master-0" in f["summary"] and "worker-0" not in f["summary"]

    def test_straggler_fires_on_gang_spread(self, tmp_path):
        state = tmp_path / "state"
        _write_status(state, KEY, "master-0",
                      _beats(100.0, 8, 0.5, step_time_ms=10.0))
        _write_status(state, KEY, "worker-0",
                      _beats(100.0, 8, 0.5, step_time_ms=10.0))
        _write_status(state, KEY, "worker-1",
                      _beats(100.0, 8, 0.5, step_time_ms=26.0))
        report, rules = _findings(state, KEY)
        assert "straggler" in rules
        f = next(f for f in report["findings"] if f["rule"] == "straggler")
        assert "worker-1" in f["summary"]
        assert f["metrics"]["spread"] == pytest.approx(2.6)

    def test_clock_alignment_feeds_the_silence_rule(self, tmp_path):
        """A replica 30s AHEAD would look alive forever on raw
        timestamps; aligned, its silence is detected."""
        state = tmp_path / "state"
        skew = 30.0
        # worker-0 stamps beats on a clock 30s ahead; it stops at true
        # t=102 while master keeps beating to t=110.
        _write_status(state, KEY, "master-0", _beats(100.0, 21, 0.5))
        _write_status(state, KEY, "worker-0",
                      _beats(100.0 + skew, 5, 0.5))
        log = ClockLog(job_clock_log(state, KEY))
        for i in range(5):
            true = 100.0 + i * 0.5
            log.observe("worker-0", true + skew, true + 0.01)
            log.observe("master-0", true, true + 0.01)
        report, rules = _findings(state, KEY)
        assert "heartbeat_silence" in rules
        f = next(
            f for f in report["findings"] if f["rule"] == "heartbeat_silence"
        )
        assert "worker-0" in f["summary"]
        assert report["clock"]["worker-0"]["offset_s"] == pytest.approx(
            -skew, abs=0.1
        )


# ---- tpujob why CLI ----


class TestWhyCLI:
    def test_why_renders_and_writes_json(self, tmp_path, capsys):
        from pytorch_operator_tpu.client.cli import main

        state = tmp_path / "state"
        _write_status(state, "default/pm", "master-0", _beats(100.0, 3, 0.5))
        _write_events(
            state, "default/pm",
            [(103.5, "Warning", "TPUJobHung", "no heartbeat; killing.")],
        )
        out = tmp_path / "report.json"
        rc = main(["--state-dir", str(state), "why", "pm", "--out", str(out)])
        assert rc == 0
        text = capsys.readouterr().out
        assert "heartbeat_silence" in text and "master-0" in text
        report = json.loads(out.read_text())
        assert report["job"] == "default/pm"
        assert [f["rule"] for f in report["findings"]] == [
            "heartbeat_silence"
        ]

    def test_why_errors_with_no_artifacts(self, tmp_path, capsys):
        from pytorch_operator_tpu.client.cli import main

        (tmp_path / "state" / "jobs").mkdir(parents=True)
        rc = main(["--state-dir", str(tmp_path / "state"), "why", "ghost"])
        assert rc == 1
        assert "no recorded artifacts" in capsys.readouterr().err


# ---- satellite: metric lifecycle (registry retirement) ----


class TestRetirement:
    def test_histogram_and_gauge_drop_series(self):
        from pytorch_operator_tpu.controller.metrics import Gauge
        from pytorch_operator_tpu.obs.metrics import Histogram

        h = Histogram("h")
        h.observe(0.1, job="a")
        h.observe(0.2, job="b")
        assert h.drop_series("job", "a") == 1
        assert h.series_count() == 1 and h.count(job="b") == 1
        g = Gauge("g")
        g.set(1.0, job="a")
        g.set(2.0, job="b", unit="x")
        assert g.drop_series("job", "b") == 1
        assert g.get(job="a") == 1.0

    def test_job_churn_leaves_registry_bounded(self, tmp_path):
        """The ROADMAP unbounded-cardinality fix: submit+observe+delete
        N jobs; the registry ends no bigger than it started."""
        from pytorch_operator_tpu.controller import FakeRunner
        from pytorch_operator_tpu.controller.supervisor import Supervisor
        from tests.testutil import new_job

        sup = Supervisor(state_dir=tmp_path / "state", runner=FakeRunner())
        try:
            def churn(i: int) -> None:
                key = sup.submit(new_job(name=f"churn-{i}", workers=0))
                m = sup.metrics
                m.step_time_seconds.observe(0.01, job=key)
                m.checkpoint_commit_seconds.observe(0.01, job=key)
                m.job_step.set(float(i), job=key)
                m.job_progress_age.set(0.1, job=key)
                assert sup.delete_job(key)

            # One warm-up fills the job-independent series (store
            # persist latency etc.); churn must not grow past it.
            churn(0)
            baseline = sup.metrics.series_count()
            for i in range(1, 25):
                churn(i)
            assert sup.metrics.series_count() <= baseline
            assert sup.metrics.step_time_seconds.series_count() == 0
            # The supervisor-side fold state retired with the series.
            assert sup._hb_observed == {} and sup._clock_seen == {}
        finally:
            sup.shutdown()


# ---- satellite: span ring / flush cadence spec knobs ----


class TestObservabilityKnobs:
    def test_policy_roundtrip_and_validation(self):
        from pytorch_operator_tpu.api import ObservabilityPolicy
        from pytorch_operator_tpu.api.validation import validate
        from pytorch_operator_tpu.api.types import TPUJob
        from tests.testutil import new_job

        p = ObservabilityPolicy(
            trace=True, trace_ring_bytes=65536, trace_flush_every=4
        )
        assert ObservabilityPolicy.from_dict(p.to_dict()) == p
        assert ObservabilityPolicy.from_dict({}).trace_ring_bytes == 0
        job = new_job(name="knobs", workers=0)
        job.spec.observability = ObservabilityPolicy(trace_ring_bytes=-1)
        with pytest.raises(Exception):
            validate(job)

    def test_env_threads_knobs_only_when_traced(self):
        from pytorch_operator_tpu.api import ObservabilityPolicy, ReplicaType
        from pytorch_operator_tpu.runtime.env import build_cluster_env
        from tests.testutil import new_job

        job = new_job(name="knobs", workers=0)
        job.spec.observability = ObservabilityPolicy(
            trace=True, trace_ring_bytes=65536, trace_flush_every=4
        )
        env = build_cluster_env(
            job, ReplicaType.MASTER, 0, trace_dir="/tmp/t"
        )
        assert env["TPUJOB_TRACE_RING_BYTES"] == "65536"
        assert env["TPUJOB_TRACE_FLUSH_EVERY"] == "4"
        env = build_cluster_env(job, ReplicaType.MASTER, 0)  # not traced
        assert "TPUJOB_TRACE_RING_BYTES" not in env

    def test_tracer_honors_env_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv(obs_trace.ENV_VAR, str(tmp_path / "t"))
        monkeypatch.setenv(obs_trace.RING_BYTES_ENV, "4096")
        monkeypatch.setenv(obs_trace.FLUSH_EVERY_ENV, "1")
        obs_trace.reset_tracer()
        try:
            rec = obs.tracer()
            assert rec.max_bytes == 4096 and rec.flush_every == 1
            # flush_every=1: the record is on disk with no flush() call.
            rec.emit("s", "cat", time.time(), 0.001)
            assert len(
                [e for e in obs_trace.load_span_file(rec.path)
                 if e["ph"] == "X"]
            ) == 1
        finally:
            monkeypatch.delenv(obs_trace.ENV_VAR, raising=False)
            monkeypatch.delenv(obs_trace.RING_BYTES_ENV, raising=False)
            monkeypatch.delenv(obs_trace.FLUSH_EVERY_ENV, raising=False)
            obs_trace.reset_tracer()

    def test_malformed_env_knobs_fall_back(self, monkeypatch):
        monkeypatch.setenv(obs_trace.RING_BYTES_ENV, "not-a-number")
        assert obs_trace._env_int(
            obs_trace.RING_BYTES_ENV, obs_trace.DEFAULT_MAX_BYTES
        ) == obs_trace.DEFAULT_MAX_BYTES
        monkeypatch.setenv(obs_trace.RING_BYTES_ENV, "-5")
        assert obs_trace._env_int(obs_trace.RING_BYTES_ENV, 7) == 7


# ---- satellite: exemplar linking ----


class TestExemplars:
    def test_observe_render_parse_roundtrip(self):
        from pytorch_operator_tpu.obs.metrics import (
            Histogram,
            parse_exemplars,
            parse_prometheus_text,
        )

        h = Histogram("tpujob_step_time_seconds")
        h.observe(0.01, exemplar="master-0/step:3", job="j")
        h.observe(0.3, exemplar="master-0/step:7", job="j")
        h.observe(0.31, job="j")  # no exemplar: keeps the last one
        text = h.render()
        assert '# {span_id="master-0/step:7"}' in text
        # The exemplar suffix must not break plain bucket parsing.
        parsed = parse_prometheus_text(text)
        from tests.testutil import assert_histogram_conformant

        assert_histogram_conformant(parsed, "tpujob_step_time_seconds")
        ex = parse_exemplars(text)["tpujob_step_time_seconds_bucket"]
        by_span = {span: v for _labels, span, v in ex}
        assert by_span == {
            "master-0/step:3": 0.01, "master-0/step:7": 0.3
        }
        assert h.exemplars(job="j")["0.5"] == ("master-0/step:7", 0.3)

    def test_top_surfaces_p99_exemplar(self, tmp_path):
        from pytorch_operator_tpu.controller.store import JobStore
        from pytorch_operator_tpu.obs import top
        from pytorch_operator_tpu.obs.metrics import Histogram
        from tests.testutil import new_job

        state = tmp_path / "state"
        store = JobStore(persist_dir=state / "jobs")
        key = store.add(new_job(name="ex", workers=0))
        _write_status(state, key, "master-0", _beats(time.time(), 2, 0.5))
        h = Histogram(top.STEP_HIST)
        h.observe(0.01, exemplar="master-0/step:1", job=key)
        h.observe(0.4, exemplar="master-0/step:2", job=key)
        (state / "metrics.prom").write_text(h.render() + "\n")
        rows = top.gather_rows(state)
        assert rows[0]["p99_span"] == "master-0/step:2"
        assert "master-0/step:2" in top.render_table(rows)

    def test_supervisor_fold_attaches_exemplars(self, tmp_path):
        from pytorch_operator_tpu.controller import FakeRunner
        from pytorch_operator_tpu.controller.supervisor import Supervisor
        from tests.testutil import new_job

        sup = Supervisor(state_dir=tmp_path / "state", runner=FakeRunner())
        try:
            key = sup.submit(new_job(name="exf", workers=0))
            # First sync creates the job (and resets its status dir —
            # beats must land after, as they do in a live world).
            sup.sync_once()
            now = time.time()
            _write_status(
                tmp_path / "state", key, "master-0",
                [{"event": "progress", "ts": now, "step": 9,
                  "steps_per_sec": 100.0, "step_time_ms": 10.0},
                 {"event": "checkpoint_committed", "ts": now, "step": 8,
                  "commit_ms": 3.0, "queue_depth": 0}],
            )
            sup.sync_once()
            assert sup.metrics.step_time_seconds.exemplars(job=key)
            ids = [
                e[0]
                for e in sup.metrics.step_time_seconds.exemplars(
                    job=key
                ).values()
            ]
            assert ids == ["master-0/step:9"]
            ck = sup.metrics.checkpoint_commit_seconds.exemplars(job=key)
            assert [e[0] for e in ck.values()] == ["master-0/ckpt_commit:8"]
        finally:
            sup.shutdown()


# ---- satellite: top sort/filter helpers ----


class TestTopKeys:
    ROWS = [
        {"job": "default/alpha", "step": 10, "steps_per_sec": 2.0,
         "p50_ms": 5.0, "p99_ms": 9.0, "ckpt_lag": 1,
         "feed_stall_ms": None, "age_s": 3.0, "restarts": 0,
         "p99_span": None},
        {"job": "default/beta", "step": 99, "steps_per_sec": 8.0,
         "p50_ms": None, "p99_ms": None, "ckpt_lag": 4,
         "feed_stall_ms": 0.5, "age_s": 1.0, "restarts": 2,
         "p99_span": "m/step:9"},
        {"job": "prod/gamma", "step": None, "steps_per_sec": None,
         "p50_ms": 7.0, "p99_ms": 30.0, "ckpt_lag": None,
         "feed_stall_ms": 2.0, "age_s": None, "restarts": 1,
         "p99_span": None},
    ]

    def test_sort_numeric_none_last(self):
        from pytorch_operator_tpu.obs.top import sort_rows

        got = [r["job"] for r in sort_rows(list(self.ROWS), "steps_per_sec")]
        assert got == ["default/beta", "default/alpha", "prod/gamma"]
        got = [
            r["job"]
            for r in sort_rows(list(self.ROWS), "steps_per_sec",
                               reverse=False)
        ]
        assert got == ["default/alpha", "default/beta", "prod/gamma"]

    def test_sort_default_is_identity(self):
        from pytorch_operator_tpu.obs.top import sort_rows

        assert sort_rows(list(self.ROWS), None) == self.ROWS

    def test_filter_substring_case_insensitive(self):
        from pytorch_operator_tpu.obs.top import filter_rows, render_table

        got = filter_rows(list(self.ROWS), "DEFAULT")
        assert [r["job"] for r in got] == ["default/alpha", "default/beta"]
        assert filter_rows(list(self.ROWS), None) == self.ROWS
        text = render_table([], filter_str="zzz")
        assert "no jobs matching" in text

    def test_render_marks_sorted_column(self):
        from pytorch_operator_tpu.obs.top import render_table

        text = render_table(list(self.ROWS), sort_key="ckpt_lag")
        assert "CKPT LAG ▾" in text


# ---- bench_smoke lane: analysis is offline-only, healthy = clean ----


@pytest.mark.bench_smoke
def test_why_is_offline_and_clean_on_healthy_run(tmp_path, capsys):
    """Two pins in one real run: (1) with tracing disabled, the whole
    run PLUS the analysis emits zero span records (analysis adds zero
    step-path span/metric calls — it reads artifacts only); (2) `tpujob
    why` on a healthy world reports zero findings."""
    from pytorch_operator_tpu.api import (
        ObjectMeta, ProcessTemplate, ReplicaSpec, ReplicaType,
        RestartPolicy, TPUJob, TPUJobSpec, set_defaults,
    )
    from pytorch_operator_tpu.client.cli import main
    from pytorch_operator_tpu.controller.supervisor import Supervisor

    obs_trace.reset_tracer()
    records_before = obs.records_emitted()
    job = TPUJob(
        metadata=ObjectMeta(name="healthy"),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.MASTER: ReplicaSpec(
                    replicas=1,
                    restart_policy=RestartPolicy.ON_FAILURE,
                    template=ProcessTemplate(
                        module="pytorch_operator_tpu.workloads.exit_with",
                        args=["--steps", "8", "--step-time", "0.02"],
                    ),
                ),
            },
        ),
    )
    set_defaults(job)
    state = tmp_path / "state"
    sup = Supervisor(state_dir=state, poll_interval=0.02)
    try:
        key = sup.submit(job)
        deadline = time.time() + 60
        while time.time() < deadline:
            sup.sync_once()
            j = sup.store.get(key)
            if j is None or j.is_finished():
                break
            time.sleep(0.02)
        sup.write_metrics_file()
        series_after_run = sup.metrics.series_count()
    finally:
        sup.shutdown()
    assert j is not None and j.is_succeeded()

    report = obs_analyze.analyze(state, key)
    assert report["findings"] == []
    assert report["replicas"]["master-0"]["beats"] >= 4
    # The estimator got real observation pairs from the daemon fold.
    assert report["clock"].get("master-0", {}).get("n", 0) >= 1
    # Offline pins: zero span records emitted by run+analysis with
    # tracing disabled, and analysis minted no new metric series.
    assert obs.records_emitted() == records_before
    assert sup.metrics.series_count() == series_after_run
    # The CLI face agrees.
    assert main(["--state-dir", str(state), "why", "healthy"]) == 0
    assert "no findings" in capsys.readouterr().out


# ---- chaos e2e: the ROADMAP drop_heartbeat world, fed to `why` ----


@pytest.mark.chaos
def test_why_names_hung_replica_from_chaos_world(tmp_path, capsys):
    """Acceptance e2e: the drop_heartbeat + hang-deadline chaos world,
    fed to `tpujob why`, names the hung replica and the
    heartbeat-silence finding, with evidence timestamped BEFORE the
    deadline kill."""
    from pytorch_operator_tpu import faults
    from pytorch_operator_tpu.api import (
        ObjectMeta, ObservabilityPolicy, ProcessTemplate, ReplicaSpec,
        ReplicaType, RestartPolicy, RunPolicy, TPUJob, TPUJobSpec,
        set_defaults,
    )
    from pytorch_operator_tpu.api.defaults import HANG_DEADLINE_ANNOTATION
    from pytorch_operator_tpu.client.cli import main
    from pytorch_operator_tpu.controller.supervisor import Supervisor
    from pytorch_operator_tpu.faults import Fault, FaultPlan

    faults.disarm()
    state = tmp_path / "state"
    sup = Supervisor(state_dir=state, poll_interval=0.05)
    key = "default/hang-why"
    try:
        faults.arm(FaultPlan(seed=1, faults=[
            Fault(kind="drop_heartbeat", target="master-0",
                  nth=3, times=100000),
        ]))
        job = TPUJob(
            metadata=ObjectMeta(
                name="hang-why",
                annotations={HANG_DEADLINE_ANNOTATION: "2"},
            ),
            spec=TPUJobSpec(
                replica_specs={
                    ReplicaType.MASTER: ReplicaSpec(
                        replicas=1,
                        restart_policy=RestartPolicy.ON_FAILURE,
                        template=ProcessTemplate(
                            module="pytorch_operator_tpu.workloads.exit_with",
                            args=["--steps", "400", "--step-time", "0.05"],
                        ),
                    ),
                },
                run_policy=RunPolicy(backoff_limit=0),
                # Trace the casualty so the silence finding can cite
                # SPAN evidence, not just status records.
                observability=ObservabilityPolicy(trace=True),
            ),
        )
        set_defaults(job)
        sup.submit(job)
        deadline = time.time() + 30
        while time.time() < deadline:
            sup.sync_once()
            j = sup.store.get(key)
            if j is None or j.is_finished():
                break
            time.sleep(0.05)
    finally:
        faults.disarm()
        sup.shutdown()
    assert j is not None and j.is_failed()

    report = obs_analyze.analyze(state, key)
    silence = [
        f for f in report["findings"] if f["rule"] == "heartbeat_silence"
    ]
    assert silence, f"no heartbeat_silence finding in {report['findings']}"
    f = silence[0]
    assert "master-0" in f["summary"]
    kill_ts = next(
        e["ts"] for e in f["evidence"] if e["source"] == "event"
    )
    pre_kill = [e for e in f["evidence"] if e["source"] != "event"]
    assert pre_kill and all(e["ts"] < kill_ts for e in pre_kill)
    # The evidence includes the victim's last step SPAN (traced world),
    # also timestamped before the kill.
    span_ev = [e for e in f["evidence"] if e["source"] == "span"]
    assert span_ev and span_ev[0]["name"] == "step"
    assert span_ev[0]["ts"] < kill_ts
    # The terminal report tells the same story.
    assert main(["--state-dir", str(state), "why", "hang-why"]) == 0
    out = capsys.readouterr().out
    assert "heartbeat_silence" in out and "master-0" in out
