"""Weight-only int8 quantization (ops/quantize.py) and its decode-path
integration (workloads/generate.py --quantize int8).

Load-bearing properties:
- per-channel symmetric quantization honors its error bound (|w - deq|
  <= scale/2 per element);
- the name→contraction-axis rule lands on the right axes of every
  llama param family (incl. scan-stacked leading ``layers`` axes and
  MoE expert banks) and leaves precision-sensitive leaves (norm scales,
  MoE router) untouched;
- generate() fed QuantizedTensor leaves is BIT-IDENTICAL to generate()
  fed the eagerly-dequantized tree — quantization changes where the
  weights live (int8 in HBM, dequant fused in-program), never the math
  downstream of dequantization.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import llama as llama_lib
from pytorch_operator_tpu.ops.quantize import (
    QuantizedTensor,
    contract_axis,
    dequantize_tree,
    quantize,
    quantize_tree,
    tree_bytes,
)
from pytorch_operator_tpu.workloads.generate import init_cache, make_generate


def _tiny_params(**cfg_over):
    import jax

    cfg = llama_lib.llama_tiny(**cfg_over)
    model = llama_lib.Llama(cfg)
    import flax.linen as nn

    params = nn.meta.unbox(
        model.init(jax.random.key(0), np.zeros((1, 8), np.int32))["params"]
    )
    return cfg, model, params


class TestQuantize:
    def test_roundtrip_error_bound(self):
        import jax

        w = jax.random.normal(jax.random.key(1), (64, 48), jnp_dtype())
        qt = quantize(w, axis=-2)
        assert qt.q.dtype == np.int8
        assert qt.scale.shape == (1, 48)
        err = np.abs(np.asarray(qt.dequantize()) - np.asarray(w))
        bound = np.asarray(qt.scale) / 2 + 1e-7
        assert (err <= bound).all()
        # Scales really are per-channel maxima / 127.
        np.testing.assert_allclose(
            np.asarray(qt.scale[0]),
            np.abs(np.asarray(w)).max(axis=0) / 127.0,
            rtol=1e-6,
        )

    def test_zero_and_extreme_channels(self):
        import jax.numpy as jnp

        w = jnp.stack(
            [jnp.zeros((8,)), jnp.full((8,), 1e30), jnp.full((8,), -3.0)],
            axis=1,
        )
        qt = quantize(w, axis=-2)
        deq = np.asarray(qt.dequantize())
        np.testing.assert_array_equal(deq[:, 0], 0.0)
        np.testing.assert_allclose(deq[:, 1], 1e30, rtol=1e-6)
        np.testing.assert_allclose(deq[:, 2], -3.0, rtol=1e-6)

    def test_rule_axes_on_llama_tree(self):
        cfg, _, params = _tiny_params()
        qtree = quantize_tree(params)
        layers = qtree["layers"]

        def scale_shape(leaf):
            assert isinstance(leaf, QuantizedTensor)
            return leaf.scale.shape

        L, D, H, K, Dh = (
            cfg.n_layers, cfg.d_model, cfg.n_heads, cfg.n_kv_heads,
            cfg.head_dim,
        )
        # q/k/v: [L, D, heads, Dh] quantized over the embed axis (-3) —
        # per-layer, per-(head, head_dim) channels.
        assert scale_shape(layers["attn"]["q_proj"]["kernel"]) == (L, 1, H, Dh)
        assert scale_shape(layers["attn"]["k_proj"]["kernel"]) == (L, 1, K, Dh)
        # o_proj [L, H*Dh, D] and MLP [L, in, out]: contraction -2.
        assert scale_shape(layers["attn"]["o_proj"]["kernel"]) == (L, 1, D)
        assert scale_shape(layers["mlp"]["gate_proj"]["kernel"]) == (
            L, 1, cfg.d_ff,
        )
        assert scale_shape(layers["mlp"]["down_proj"]["kernel"]) == (L, 1, D)
        # Embed rows; head columns.
        assert scale_shape(qtree["embed"]["embedding"]) == (cfg.vocab_size, 1)
        assert scale_shape(qtree["lm_head"]["kernel"]) == (1, cfg.vocab_size)
        # Norm scales stay full-precision arrays.
        assert not isinstance(
            layers["attn_norm"]["scale"], QuantizedTensor
        )
        assert not isinstance(qtree["final_norm"]["scale"], QuantizedTensor)

    def test_moe_banks_quantized_router_kept(self):
        cfg, _, params = _tiny_params(n_experts=4, moe_aux_weight=1e-2)
        qtree = quantize_tree(params)
        moe = qtree["layers"]["moe_mlp"]
        assert isinstance(moe["w_in"], QuantizedTensor)
        assert moe["w_in"].scale.shape == (
            cfg.n_layers, cfg.n_experts, 1, cfg.d_ff,
        )
        assert isinstance(moe["w_out"], QuantizedTensor)
        # The router's argmax is precision-sensitive — never quantized.
        assert not isinstance(moe["gate"], QuantizedTensor)

    def test_unknown_quantize_mode_rejected_at_config(self):
        import pytest

        with pytest.raises(ValueError, match="quantize"):
            llama_lib.llama_tiny(quantize="int4")

    def test_rule_skips_low_rank_leaves(self):
        import jax.numpy as jnp

        assert contract_axis(("anything", "kernel"), jnp.zeros((4,))) is None
        assert contract_axis(("x", "scale"), jnp.zeros((4, 4))) is None

    def test_tree_bytes_quarter_of_f32(self):
        _, _, params = _tiny_params()
        import jax

        f32 = sum(p.size * 4 for p in jax.tree.leaves(params))
        q = tree_bytes(quantize_tree(params))
        # int8 payload + scales + the unquantized norm leaves: well under
        # half, approaching a quarter.
        assert q < 0.30 * f32

    def test_dequantize_tree_identity_on_plain_trees(self):
        _, _, params = _tiny_params()
        out = dequantize_tree(params)
        import jax

        assert jax.tree.structure(out) == jax.tree.structure(params)
        for a, b in zip(jax.tree.leaves(out), jax.tree.leaves(params)):
            assert a is b

    def test_forward_logits_survive_quantization(self):
        """End-to-end accuracy proxy: full-forward logits through the
        quantized weights stay close (normalized RMS) to the original's
        — per-channel int8 at 127 levels is a sub-percent weight error."""
        cfg, model, params = _tiny_params()
        toks = np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 8))
        toks = toks.astype(np.int32)
        ref = np.asarray(model.apply({"params": params}, toks))
        deq = dequantize_tree(quantize_tree(params))
        got = np.asarray(model.apply({"params": deq}, toks))
        rms = np.sqrt(((got - ref) ** 2).mean()) / np.sqrt((ref**2).mean())
        assert rms < 0.02, rms


class TestQuantizedGenerate:
    def test_quantized_generate_bit_identical_to_eager_dequant(self):
        """THE integration invariant: a quantize-mode model fed
        QuantizedTensor leaves (dequant inside the scan body, int8 in
        HBM) produces exactly the tokens of the same program fed the
        eagerly-dequantized tree — same math, different residency.
        (map_variables' trans_in is identity on plain arrays, so one
        jitted program serves both sides of the A/B.)"""
        import jax

        new = 8
        cfg = llama_lib.llama_tiny(
            decode=True, max_decode_len=16, quantize="int8"
        )
        decode_model = llama_lib.Llama(cfg)
        _, _, params = _tiny_params()
        qparams = jax.jit(quantize_tree)(params)

        gen = make_generate(decode_model, max_new_tokens=new)
        prompt = np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 8))
        import jax.numpy as jnp

        prompt = jnp.asarray(prompt, jnp.int32)

        cache = init_cache(decode_model, 2, 8)
        t_q, _ = gen(qparams, cache, prompt, jax.random.key(0))
        cache = init_cache(decode_model, 2, 8)
        t_e, _ = gen(dequantize_tree(qparams), cache, prompt, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(t_q), np.asarray(t_e))

    def test_quantize_mode_full_forward_matches_plain_model(self):
        """Llama(quantize='int8').apply on the quantized tree ==
        plain Llama.apply on the eagerly dequantized tree, exactly —
        the in-module map_variables hook rearranges residency, not
        numerics. Also: a quantize-mode model refuses to init."""
        import jax
        import pytest

        cfg, model, params = _tiny_params()
        qcfg = dataclasses.replace(cfg, quantize="int8")
        qmodel = llama_lib.Llama(qcfg)
        qparams = quantize_tree(params)
        toks = np.random.default_rng(3).integers(
            0, cfg.vocab_size, (2, 8)
        ).astype(np.int32)
        got = qmodel.apply({"params": qparams}, toks)
        ref = model.apply({"params": dequantize_tree(qparams)}, toks)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(ref))
        with pytest.raises(ValueError, match="quantize-mode"):
            qmodel.init(jax.random.key(0), toks)

    def test_run_quantized_smoke(self, tmp_path):
        """The workload path end to end on CPU (the chip measurements in
        BASELINE.md ride this exact entry)."""
        from pytorch_operator_tpu.workloads import generate as gen_mod

        result = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=4,
            quantize="int8", log=lambda *a: None,
        )
        assert result["quantize"] == "int8"
        assert result["value"] > 0
        assert result["weight_mb"] > 0

    def test_init_host_requires_quantize(self):
        import pytest

        from pytorch_operator_tpu.workloads import generate as gen_mod

        with pytest.raises(ValueError, match="init_host"):
            gen_mod.run(config="tiny", init_host=True, log=lambda *a: None)
        with pytest.raises(ValueError, match="compare_unquantized"):
            gen_mod.run(
                config="tiny", quantize="int8", init_host=True,
                compare_unquantized=True, log=lambda *a: None,
            )

    def test_compare_unquantized_reports_control(self):
        from pytorch_operator_tpu.workloads import generate as gen_mod

        result = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=4,
            quantize="int8", compare_unquantized=True, log=lambda *a: None,
        )
        assert result["tokens_per_sec_per_chip_unquantized"] > 0
        assert result["int8_speedup"] > 0

    @pytest.mark.slow
    def test_init_host_path_runs(self):
        """Host-init + host-quantize + device_put (the 8B-on-one-chip
        path) — on CPU the 'transfer' is trivial but the code path and
        tree plumbing are identical."""
        from pytorch_operator_tpu.workloads import generate as gen_mod

        result = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=4,
            quantize="int8", init_host=True, log=lambda *a: None,
        )
        assert result["quantize"] == "int8"


class TestKVQuantize:
    def _decode_models(self):
        cfg = llama_lib.llama_tiny(decode=True, max_decode_len=16)
        q_cfg = dataclasses.replace(cfg, kv_quantize="int8")
        return llama_lib.Llama(cfg), llama_lib.Llama(q_cfg)

    def test_cache_layout_int8_with_scales(self):
        _, qmodel = self._decode_models()
        cache = init_cache(qmodel, 2, 8)
        assert set(cache) == {
            f"layer_{i}" for i in range(qmodel.cfg.n_layers)
        }
        layer = cache["layer_0"]["attn"]
        assert layer["cached_key"].dtype == np.int8
        assert layer["cached_value"].dtype == np.int8
        # Heads-major slabs, per-(token, kv-head) f32 scales: one per
        # head_dim payload row.
        assert layer["key_scale"].shape == (
            2, qmodel.cfg.n_kv_heads, 16, 1,
        )
        assert layer["key_scale"].dtype == np.float32

    @pytest.mark.slow
    def test_decode_forward_matches_flax_apply(self):
        """The unrolled serving path (decode_forward — flat per-layer
        cache, token-slice writes) is numerically IDENTICAL to the flax
        scan-lifted decode apply, with and without the int8 cache."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.models.llama import (
            decode_forward,
            init_decode_cache,
        )

        _, _, params = _tiny_params()
        toks = jnp.asarray(
            np.random.default_rng(7).integers(0, 256, (2, 8)), jnp.int32
        )
        for kv in (None, "int8"):
            cfg = llama_lib.llama_tiny(
                decode=True, max_decode_len=16, kv_quantize=kv
            )
            model = llama_lib.Llama(cfg)
            flax_cache = jax.tree.map(
                lambda s: jnp.zeros(s.shape, s.dtype),
                jax.eval_shape(
                    lambda k: model.init(k, np.zeros((2, 8), np.int32)),
                    jax.random.key(0),
                )["cache"],
            )
            nxt = jnp.full((2, 1), 3, jnp.int32)
            pos = jnp.full((2, 1), 8, jnp.int32)
            # Flax path: prefill then one decode step.
            ref_h, upd = model.apply(
                {"params": params, "cache": flax_cache},
                toks,
                return_hidden=True,
                mutable=["cache"],
            )
            ref_h2, _ = model.apply(
                {"params": params, "cache": upd["cache"]},
                nxt,
                pos,
                return_hidden=True,
                mutable=["cache"],
            )
            # Functional path, same inputs. Tolerance, not bit-identity:
            # the flax path executes the layer stack as one compiled
            # lax.scan while this path unrolls it, and XLA's fusion
            # boundaries differ — last-ulp reassociation only (the
            # greedy-rollout gold test pins token-level equality).
            cache = init_decode_cache(cfg, 2)
            got_h, cache = decode_forward(model, params, cache, toks)
            got_h2, _ = decode_forward(model, params, cache, nxt, pos)
            np.testing.assert_allclose(
                np.asarray(got_h), np.asarray(ref_h), rtol=2e-5, atol=2e-6
            )
            np.testing.assert_allclose(
                np.asarray(got_h2), np.asarray(ref_h2), rtol=2e-5, atol=2e-6
            )

    def test_prefill_outputs_close_to_fp_cache(self):
        """The int8 cache changes K/V by at most scale/2 per element —
        prefill hidden states must track the fp-cache path within the
        quantization error, not diverge structurally."""
        import jax

        model, qmodel = self._decode_models()
        _, _, params = _tiny_params()
        toks = np.random.default_rng(5).integers(0, 256, (2, 8))
        toks = toks.astype(np.int32)

        def prefill(m):
            # No cache passed: the flax path zero-initializes its own
            # (scan-stacked) cache under mutable — init_cache's flat
            # decode_forward layout would be silently ignored here.
            out, _ = m.apply(
                {"params": params},
                toks,
                return_hidden=True,
                mutable=["cache"],
            )
            return np.asarray(jax.block_until_ready(out))

        ref, got = prefill(model), prefill(qmodel)
        rms = np.sqrt(((got - ref) ** 2).mean()) / np.sqrt((ref**2).mean())
        assert rms < 0.02, rms

    @pytest.mark.slow
    def test_generate_runs_and_tracks_fp_rollout(self):
        """End to end through make_generate: the int8-cache rollout is
        valid tokens; on this tiny model the greedy path stays within
        the fp rollout for at least the first steps (argmax margins at
        random init are far wider than the cache quantization error)."""
        import jax
        import jax.numpy as jnp

        model, qmodel = self._decode_models()
        _, _, params = _tiny_params()
        prompt = jnp.asarray(
            np.random.default_rng(6).integers(0, 256, (2, 8)), jnp.int32
        )
        new = 6
        t_fp, _ = make_generate(model, max_new_tokens=new)(
            params, init_cache(model, 2, 8), prompt, jax.random.key(0)
        )
        t_q, _ = make_generate(qmodel, max_new_tokens=new)(
            params, init_cache(qmodel, 2, 8), prompt, jax.random.key(0)
        )
        assert t_q.shape == (2, new)
        assert ((0 <= np.asarray(t_q)) & (np.asarray(t_q) < 256)).all()
        np.testing.assert_array_equal(
            np.asarray(t_q)[:, :2], np.asarray(t_fp)[:, :2]
        )

    @pytest.mark.slow
    def test_moe_decode_forward_matches_flax_apply(self):
        """The unrolled serving path must also carry MoE blocks (router
        + expert banks slice per layer like any stacked leaf)."""
        import jax.numpy as jnp

        from pytorch_operator_tpu.models.llama import (
            decode_forward,
            init_decode_cache,
        )

        _, _, params = _tiny_params(n_experts=4, moe_top_k=2)
        cfg = llama_lib.llama_tiny(
            decode=True, max_decode_len=16, n_experts=4, moe_top_k=2
        )
        model = llama_lib.Llama(cfg)
        toks = jnp.asarray(
            np.random.default_rng(8).integers(0, 256, (2, 8)), jnp.int32
        )
        ref, _ = model.apply(
            {"params": params},
            toks,
            return_hidden=True,
            mutable=["cache"],
        )
        got, _ = decode_forward(
            model, params, init_decode_cache(cfg, 2), toks
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=2e-5, atol=2e-6
        )

    @pytest.mark.slow
    def test_quantized_moe_decode_runs(self):
        """Quantized expert banks (w_in/w_out QuantizedTensors) slice
        and dequantize per layer through the serving path."""
        import jax.numpy as jnp

        from pytorch_operator_tpu.models.llama import (
            decode_forward,
            init_decode_cache,
        )

        _, _, params = _tiny_params(n_experts=4, moe_top_k=2)
        qparams = quantize_tree(params)
        cfg = llama_lib.llama_tiny(
            decode=True, max_decode_len=16, n_experts=4, moe_top_k=2,
            quantize="int8",
        )
        model = llama_lib.Llama(cfg)
        toks = jnp.asarray(
            np.random.default_rng(9).integers(0, 256, (2, 8)), jnp.int32
        )
        got_q, _ = decode_forward(
            model, qparams, init_decode_cache(cfg, 2), toks
        )
        ref, _ = decode_forward(
            model, dequantize_tree(qparams), init_decode_cache(cfg, 2), toks
        )
        np.testing.assert_array_equal(np.asarray(got_q), np.asarray(ref))

    @pytest.mark.slow
    def test_decode_forward_tp_sharded_matches_unsharded(self):
        """Distributed serving: decode_forward under a dp×fsdp×tp mesh
        with born-sharded params (logical rules: heads/mlp/vocab over
        tp, embed over fsdp, batch over dp) produces the unsharded
        path's hidden states — SPMD partitioning changes collectives,
        not semantics."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from pytorch_operator_tpu.models.llama import (
            decode_forward,
            init_decode_cache,
        )
        from pytorch_operator_tpu.parallel import make_mesh
        from pytorch_operator_tpu.parallel.logical import init_sharded

        cfg = llama_lib.llama_tiny(decode=True, max_decode_len=16)
        model = llama_lib.Llama(cfg)
        train_model = llama_lib.Llama(
            dataclasses.replace(cfg, decode=False)
        )

        def init_fn(key):
            return train_model.init(key, np.zeros((1, 8), np.int32))[
                "params"
            ]

        mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        sh_params, _ = init_sharded(init_fn, mesh, jax.random.key(0))
        _, _, ref_params = _tiny_params()  # same seed, unsharded

        toks = jnp.asarray(
            np.random.default_rng(11).integers(0, 256, (2, 8)), jnp.int32
        )
        ref_h, _ = decode_forward(
            model, ref_params, init_decode_cache(cfg, 2), toks
        )
        got_h, _ = jax.jit(
            lambda p, c, t: decode_forward(model, p, c, t)
        )(sh_params, init_decode_cache(cfg, 2), toks)
        np.testing.assert_allclose(
            np.asarray(got_h), np.asarray(ref_h), rtol=2e-4, atol=2e-5
        )

    def test_unknown_kv_mode_rejected(self):
        import pytest

        with pytest.raises(ValueError, match="kv_quantize"):
            llama_lib.llama_tiny(kv_quantize="fp8")

    def test_run_kv_quantized_smoke(self):
        from pytorch_operator_tpu.workloads import generate as gen_mod

        result = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=4,
            kv_quantize="int8", max_decode_len=32, log=lambda *a: None,
        )
        assert result["kv_quantize"] == "int8"
        assert result["max_decode_len"] == 32
        assert result["value"] > 0


def jnp_dtype():
    import jax.numpy as jnp

    return jnp.float32
