"""Flight-recorder primitives (obs/): Histogram exposition conformance,
SpanRecorder ring files, the Chrome-trace merger, and `tpujob top`.

Satellite coverage for the observability PR:

- Prometheus exposition conformance for the new ``Histogram`` — bucket
  monotonicity, ``+Inf`` bucket == ``_count``, label escaping shared
  with the Counter/Gauge ``_fmt_labels`` (a hostile label value must
  render identically across families and parse back exactly);
- SpanRecorder ring-file rotation and writer-crash torn lines (the
  merger must skip a torn last line by contract);
- zero-overhead-when-disabled: with ``TPUJOB_TRACE_DIR`` unset the span
  helpers return one shared nullcontext and emit nothing.
"""

from __future__ import annotations

import contextlib
import json
import time

import pytest

from pytorch_operator_tpu import obs
from pytorch_operator_tpu.controller.metrics import Counter, MetricsRegistry
from pytorch_operator_tpu.obs import metrics as obs_metrics
from pytorch_operator_tpu.obs import trace as obs_trace
from pytorch_operator_tpu.obs.metrics import (
    DEFAULT_BUCKETS,
    Histogram,
    histogram_quantile,
    parse_prometheus_text,
)
from tests.testutil import assert_histogram_conformant


@pytest.fixture
def traced_dir(tmp_path, monkeypatch):
    """Arm the process tracer at a tmp dir; disarm + close on exit."""
    d = tmp_path / "trace"
    monkeypatch.setenv(obs_trace.ENV_VAR, str(d))
    obs_trace.reset_tracer()
    yield d
    monkeypatch.delenv(obs_trace.ENV_VAR, raising=False)
    obs_trace.reset_tracer()


# ---- Histogram ----


class TestHistogram:
    def test_bucket_grid_must_strictly_increase(self):
        with pytest.raises(ValueError):
            Histogram("h", buckets=(1.0, 1.0, 2.0))

    def test_exposition_conformance(self):
        h = Histogram("tpujob_test_seconds", "help text")
        for v in (0.00005, 0.0002, 0.003, 0.003, 0.07, 1.2, 999.0):
            h.observe(v, job="a")
        for v in (0.01, 0.02):
            h.observe(v, job="b")
        text = h.render()
        assert "# TYPE tpujob_test_seconds histogram" in text
        parsed = parse_prometheus_text(text)
        assert_histogram_conformant(parsed, "tpujob_test_seconds")
        # Exact invariants beyond shape: +Inf == count, sum == total.
        assert h.count(job="a") == 7
        assert h.count(job="b") == 2
        assert h.sum(job="b") == pytest.approx(0.03)
        inf_a = [
            v for labels, v in parsed["tpujob_test_seconds_bucket"]
            if labels.get("job") == "a" and labels["le"] == "+Inf"
        ]
        assert inf_a == [7]
        # 999.0 overflows the largest finite bucket: the largest finite
        # le must hold 6, +Inf all 7.
        top_fin = [
            v for labels, v in parsed["tpujob_test_seconds_bucket"]
            if labels.get("job") == "a"
            and labels["le"] == f"{max(DEFAULT_BUCKETS):g}"
        ]
        assert top_fin == [6]

    def test_boundary_value_is_inclusive(self):
        # Prometheus le is <=: an observation equal to a bound lands in
        # that bound's bucket.
        h = Histogram("h", buckets=(0.1, 1.0))
        h.observe(0.1)
        parsed = parse_prometheus_text(h.render())
        by_le = {labels["le"]: v for labels, v in parsed["h_bucket"]}
        assert by_le == {"0.1": 1, "1": 1, "+Inf": 1}

    def test_label_escaping_shared_with_counter(self):
        hostile = 'evil"job\\with\nnewline'
        h = Histogram("h_total_seconds")
        h.observe(0.5, job=hostile)
        c = Counter("c_total")
        c.inc(1, job=hostile)
        h_line = next(
            ln for ln in h.render().splitlines() if ln.startswith("h_total_seconds_sum")
        )
        c_line = next(
            ln for ln in c.render().splitlines() if "{" in ln
        )
        # Identical escaped label blob across metric families.
        h_blob = h_line[h_line.index("{") + 1:h_line.rindex("}")]
        c_blob = c_line[c_line.index("{") + 1:c_line.rindex("}")]
        assert h_blob == c_blob
        # And the parser inverts the escaping exactly.
        parsed = parse_prometheus_text(h.render())
        labels, _ = parsed["h_total_seconds_count"][0]
        assert labels["job"] == hostile

    def test_empty_histogram_renders_family_only(self):
        h = Histogram("h_empty", "nothing yet")
        text = h.render()
        assert "# TYPE h_empty histogram" in text
        assert "h_empty_bucket" not in text

    def test_quantile_interpolation(self):
        h = Histogram("h", buckets=(1.0, 2.0, 4.0))
        for _ in range(100):
            h.observe(1.5)
        # All mass in (1, 2]: p50 interpolates inside that bucket.
        q = h.quantile(0.5)
        assert 1.0 < q <= 2.0
        # +Inf-bucket mass clamps to the largest finite bound.
        h2 = Histogram("h2", buckets=(1.0,))
        h2.observe(50.0)
        assert h2.quantile(0.99) == 1.0
        assert h2.quantile(0.5, job="missing") is None

    def test_histogram_quantile_helper_edge_cases(self):
        assert histogram_quantile([], 0.5) is None
        assert histogram_quantile([(1.0, 0), (float("inf"), 0)], 0.5) is None
        cum = [(1.0, 10), (2.0, 10), (float("inf"), 10)]
        # Flat tail: quantile stays at the first bound that covers rank.
        assert histogram_quantile(cum, 0.99) <= 1.0

    def test_registry_serves_histograms(self):
        reg = MetricsRegistry()
        h = reg.histogram("tpujob_extra_seconds", "x")
        assert reg.histogram("tpujob_extra_seconds") is h
        h.observe(0.2, job="j")
        reg.sync_pass_seconds.observe(0.01, phase="total")
        text = reg.render_text()
        parsed = parse_prometheus_text(text)
        assert_histogram_conformant(parsed, "tpujob_extra_seconds")
        assert_histogram_conformant(parsed, "tpujob_sync_pass_seconds")
        assert text.endswith("\n")

    def test_parser_skips_garbage_lines(self):
        text = "a_metric 1.5\nnot a metric line at all\nb{x=\"y\"} nan?\n"
        parsed = parse_prometheus_text(text)
        assert parsed == {"a_metric": [({}, 1.5)]}


# ---- SpanRecorder / tracer ----


class TestSpanRecorderDisabled:
    def test_disabled_is_shared_nullcontext_and_zero_records(self, monkeypatch):
        monkeypatch.delenv(obs_trace.ENV_VAR, raising=False)
        obs_trace.reset_tracer()
        assert obs.tracer() is None
        assert not obs.trace_enabled()
        before = obs.records_emitted()
        cm = obs.span("step", cat="step", step=1)
        # THE zero-overhead contract: one shared nullcontext, no
        # allocation, nothing emitted.
        assert cm is obs_trace._NULL
        with cm:
            pass
        obs.instant("marker")
        assert obs.records_emitted() == before


class TestSpanRecorder:
    def test_spans_recorded_with_chrome_fields(self, traced_dir):
        with obs.span("step", cat="step", step=3):
            time.sleep(0.002)
        obs.instant("kill", cat="fault", target="worker-0")
        rec = obs.tracer()
        rec.flush()
        events = obs_trace.load_span_file(rec.path)
        meta = [e for e in events if e["ph"] == "M"]
        spans = [e for e in events if e["ph"] == "X"]
        assert {m["name"] for m in meta} >= {"process_name", "clock_sync"}
        step = next(e for e in spans if e["name"] == "step")
        assert step["cat"] == "step"
        assert step["args"] == {"step": 3}
        assert step["dur"] >= 2000  # microseconds
        for field in ("ts", "dur", "pid", "tid"):
            assert isinstance(step[field], (int, float))
        kill = next(e for e in spans if e["name"] == "kill")
        assert kill["dur"] == 0

    def test_ring_rotation_keeps_two_generations(self, tmp_path):
        rec = obs_trace.SpanRecorder(tmp_path, "proc", max_bytes=4096)
        for i in range(400):
            rec.emit("s", "cat", time.time(), 0.001, i=i, pad="x" * 40)
        rec.close()
        files = obs_trace.span_files(tmp_path)
        assert rec.path in files
        rotated = rec.path.with_suffix(".jsonl.1")
        assert rotated in files
        # Ring bound: current generation respects max_bytes; older
        # generations beyond .1 were dropped, not accumulated.
        assert rec.path.stat().st_size <= 4096
        assert len(files) == 2
        # The merge spans both generations and the new generation is
        # self-describing (a process_name metadata record re-emitted).
        doc = obs_trace.merge_trace_files(files)
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
        assert spans[-1]["args"]["i"] == 399
        assert len(spans) > 1
        cur_events = obs_trace.load_span_file(rec.path)
        assert any(e["ph"] == "M" for e in cur_events)

    def test_torn_last_line_is_skipped_by_merger(self, tmp_path):
        rec = obs_trace.SpanRecorder(tmp_path, "crashy")
        rec.emit("good", "cat", 1.0, 0.5)
        rec.close()
        # A SIGKILLed writer tears its buffered tail: append half a
        # record with no newline, plus a foreign line for good measure.
        with open(rec.path, "ab") as f:
            f.write(b'not json at all\n')
            f.write(b'[1, 2, 3]\n')  # JSON, but not a span record
            f.write(b'{"name": "half", "ph": "X", "ts": 12')
        events = obs_trace.load_span_file(rec.path)
        spans = [e for e in events if e["ph"] == "X"]
        assert [s["name"] for s in spans] == ["good"]
        # ph=X records missing ts/dur are dropped too.
        with open(rec.path, "ab") as f:
            f.write(b'\n{"name": "no-ts", "ph": "X"}\n')
        spans = [
            e for e in obs_trace.load_span_file(rec.path) if e["ph"] == "X"
        ]
        assert [s["name"] for s in spans] == ["good"]

    def test_load_missing_file_is_empty(self, tmp_path):
        assert obs_trace.load_span_file(tmp_path / "nope.jsonl") == []

    def test_reset_rereads_env(self, traced_dir):
        assert obs.trace_enabled()
        first = obs.tracer()
        obs_trace.reset_tracer()
        second = obs.tracer()
        assert second is not first and second is not None


class TestMerge:
    def _mk(self, tmp_path, name, ts_list):
        rec = obs_trace.SpanRecorder(tmp_path, name)
        for ts in ts_list:
            rec.emit("e", "cat", ts, 0.001, src=name)
        rec.close()
        return rec.path

    def test_merge_sorts_and_keeps_meta_first(self, tmp_path):
        a = self._mk(tmp_path, "a", [3.0, 1.0])
        b = self._mk(tmp_path, "b", [2.0])
        doc = obs_trace.merge_trace_files([a, b])
        assert doc["displayTimeUnit"] == "ms"
        events = doc["traceEvents"]
        spans = [e for e in events if e.get("ph") == "X"]
        metas = [e for e in events if e.get("ph") == "M"]
        assert metas and events[:len(metas)] == metas
        assert [s["ts"] for s in spans] == sorted(s["ts"] for s in spans)
        # The whole document is valid Chrome-trace JSON.
        json.loads(json.dumps(doc))

    def test_clock_offsets_shift_spans_not_meta(self, tmp_path):
        a = self._mk(tmp_path, "a", [1.0])
        doc = obs_trace.merge_trace_files([a], clock_offsets={a: 2.0})
        span = next(e for e in doc["traceEvents"] if e.get("ph") == "X")
        assert span["ts"] == pytest.approx(3.0e6)


# ---- reconciler trace-dir injection (spec knob vs global) ----


class TestTraceDirInjection:
    def _reconciler(self, tmp_path):
        from pytorch_operator_tpu.controller import (
            EventRecorder,
            FakeRunner,
            GangScheduler,
            JobStore,
            Reconciler,
        )

        return Reconciler(
            store=JobStore(),
            runner=FakeRunner(),
            events=EventRecorder(),
            metrics=MetricsRegistry(),
            gang=GangScheduler(enabled=True),
            trace_root=tmp_path / "trace",
        )

    def test_spec_knob_arms_per_job_dir(self, tmp_path, monkeypatch):
        from pytorch_operator_tpu.api import ObservabilityPolicy
        from tests.testutil import new_job

        monkeypatch.delenv(obs_trace.ENV_VAR, raising=False)
        obs_trace.reset_tracer()
        rec = self._reconciler(tmp_path)
        job = new_job(name="traced")
        assert rec._trace_dir(job, "default/traced") is None
        job.spec.observability = ObservabilityPolicy(trace=True)
        d = rec._trace_dir(job, "default/traced")
        assert d is not None and d.endswith("default_traced")

    def test_global_tracing_traces_every_job(self, tmp_path, monkeypatch):
        from tests.testutil import new_job

        monkeypatch.setenv(obs_trace.ENV_VAR, str(tmp_path / "sup-trace"))
        obs_trace.reset_tracer()
        try:
            rec = self._reconciler(tmp_path)
            job = new_job(name="plain")  # no spec opt-in
            assert rec._trace_dir(job, "default/plain") is not None
        finally:
            monkeypatch.delenv(obs_trace.ENV_VAR, raising=False)
            obs_trace.reset_tracer()

    def test_env_builder_clears_inherited_trace_dir(self):
        from pytorch_operator_tpu.api import ReplicaType
        from pytorch_operator_tpu.runtime.env import build_cluster_env
        from tests.testutil import new_job

        job = new_job(name="envjob")
        env = build_cluster_env(job, ReplicaType.WORKER, 0)
        # A traced supervisor must not leak ITS trace dir into replicas.
        assert env["TPUJOB_TRACE_DIR"] == ""
        env = build_cluster_env(
            job, ReplicaType.WORKER, 0, trace_dir="/tmp/t"
        )
        assert env["TPUJOB_TRACE_DIR"] == "/tmp/t"


# ---- device-feed spans (the data-plane layer of the trace) ----


class TestDeviceFeedSpans:
    def test_feed_thread_spans_and_stall_stats(self, traced_dir):
        from pytorch_operator_tpu.data.device_prefetch import DevicePrefetcher

        pf = DevicePrefetcher(lambda: 1, put=lambda x: x + 1, depth=2)
        try:
            assert [pf.get() for _ in range(4)] == [2, 2, 2, 2]
            stats = pf.stats()
        finally:
            pf.close()
        assert stats["gets"] == 4 and stats["batches"] >= 4
        assert stats["feed_stall_ms_avg"] >= 0.0
        rec = obs.tracer()
        rec.flush()
        names = {
            e["name"]
            for e in obs_trace.load_span_file(rec.path)
            if e["ph"] == "X"
        }
        assert {"feed_produce", "feed_put"} <= names


# ---- tpujob top ----


class TestTop:
    def _seed_state(self, tmp_path):
        from pytorch_operator_tpu.controller.progress import job_status_dir
        from pytorch_operator_tpu.controller.store import JobStore
        from tests.testutil import new_job

        state = tmp_path / "state"
        store = JobStore(persist_dir=state / "jobs")
        job = new_job(name="live", workers=0)
        key = store.add(job)
        now = time.time()
        d = job_status_dir(state / "status", key)
        d.mkdir(parents=True)
        recs = [
            {"event": "progress", "ts": now - 1, "step": 40,
             "steps_per_sec": 8.0, "feed_stall_ms": 0.25},
            {"event": "checkpoint_committed", "ts": now - 2, "step": 35,
             "commit_ms": 12.0, "queue_depth": 1, "oldest_age_s": 0.1},
        ]
        (d / "master-0.jsonl").write_text(
            "".join(json.dumps(r) + "\n" for r in recs)
        )
        from pytorch_operator_tpu.obs.top import STEP_HIST

        h = Histogram(STEP_HIST)
        for v in (0.1, 0.12, 0.3):
            h.observe(v, job=key)
        (state / "metrics.prom").write_text(h.render() + "\n")
        return state, key

    def test_gather_rows_and_render(self, tmp_path):
        from pytorch_operator_tpu.obs import top

        state, key = self._seed_state(tmp_path)
        rows = top.gather_rows(state)
        assert len(rows) == 1
        r = rows[0]
        assert r["job"] == key
        assert r["step"] == 40.0
        assert r["ckpt_lag"] == 5
        assert r["steps_per_sec"] == 8.0
        assert r["feed_stall_ms"] == 0.25
        assert r["p50_ms"] is not None and r["p99_ms"] >= r["p50_ms"]
        assert r["age_s"] >= 0.5
        text = top.render_table(rows)
        assert "CKPT LAG" in text and key in text

    def test_empty_state_renders_placeholder(self, tmp_path):
        from pytorch_operator_tpu.obs import top

        out = top.render(tmp_path / "fresh")
        assert "(no active jobs)" in out
