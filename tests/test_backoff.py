"""The shared jittered-backoff retry helper (backoff.py) and its
rendezvous integration — the hardening that replaced the fixed-interval
retry loop (thundering-herd joins) and gave checkpoint I/O a retry at
all."""

import pytest

from pytorch_operator_tpu.backoff import Backoff, retry_call


class TestBackoffSchedule:
    def test_deterministic_per_seed(self):
        a = Backoff(base_s=0.1, cap_s=5.0, seed=3)
        b = Backoff(base_s=0.1, cap_s=5.0, seed=3)
        assert a.delays(8) == b.delays(8)

    def test_seeds_decorrelate(self):
        a = Backoff(base_s=0.1, cap_s=5.0, seed=0)
        b = Backoff(base_s=0.1, cap_s=5.0, seed=1)
        assert a.delays(8) != b.delays(8)

    def test_exponential_growth_then_cap(self):
        b = Backoff(base_s=0.5, cap_s=4.0, jitter=0.0)
        assert b.delays(5) == [0.5, 1.0, 2.0, 4.0, 4.0]

    def test_jitter_bounded(self):
        b = Backoff(base_s=1.0, cap_s=1.0, jitter=0.25, seed=9)
        for d in b.delays(32):
            assert 0.75 <= d <= 1.25

    def test_no_wall_clock_randomness(self):
        # Same object, same attempt -> same delay, always.
        b = Backoff(seed=5)
        assert b.delay(3) == b.delay(3)

    def test_huge_attempt_caps_instead_of_overflowing(self):
        # An unbounded attempt counter (an idle poll loop running for
        # hours) must land on the cap, not OverflowError float pow.
        b = Backoff(base_s=0.0005, cap_s=0.05, factor=2.0, jitter=0.0)
        assert b.delay(1024) == 0.05
        assert b.delay(10**9) == 0.05
        # Capped delays keep per-attempt jitter decorrelation.
        j = Backoff(base_s=0.0005, cap_s=0.05, factor=2.0, jitter=0.1)
        assert j.delay(2000) != j.delay(2001)


class TestRetryCall:
    def test_retries_then_succeeds(self):
        calls = []

        def fn():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        slept = []
        out = retry_call(
            fn,
            backoff=Backoff(base_s=0.01, jitter=0.0),
            attempts=5,
            retry_on=(OSError,),
            sleep=slept.append,
        )
        assert out == "ok"
        assert len(calls) == 3
        assert slept == [0.01, 0.02]

    def test_attempts_exhausted_reraises(self):
        def fn():
            raise OSError("always")

        with pytest.raises(OSError, match="always"):
            retry_call(
                fn,
                backoff=Backoff(base_s=0.0, jitter=0.0),
                attempts=3,
                retry_on=(OSError,),
                sleep=lambda d: None,
            )

    def test_timeout_contract(self):
        # A fake clock: every attempt costs 1s; the deadline cuts the
        # retry loop even though attempts is unbounded.
        t = [0.0]

        def clock():
            return t[0]

        def fn():
            t[0] += 1.0
            raise ValueError("down")

        with pytest.raises(ValueError):
            retry_call(
                fn,
                backoff=Backoff(base_s=0.1, jitter=0.0),
                timeout_s=3.0,
                retry_on=(ValueError,),
                sleep=lambda d: None,
                clock=clock,
            )
        assert t[0] <= 4.0  # stopped at the deadline, not much past it

    def test_on_retry_cleanup_hook(self):
        seen = []

        def fn():
            if len(seen) < 1:
                raise OSError("partial write")
            return 42

        assert (
            retry_call(
                fn,
                backoff=Backoff(base_s=0.0, jitter=0.0),
                attempts=3,
                retry_on=(OSError,),
                on_retry=lambda e, a: seen.append((str(e), a)),
                sleep=lambda d: None,
            )
            == 42
        )
        assert seen == [("partial write", 1)]

    def test_unlisted_exception_propagates_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise KeyError("not transient")

        with pytest.raises(KeyError):
            retry_call(
                fn, backoff=Backoff(), attempts=5, retry_on=(OSError,),
                sleep=lambda d: None,
            )
        assert len(calls) == 1

    def test_needs_a_bound(self):
        with pytest.raises(ValueError):
            retry_call(lambda: 1, backoff=Backoff())


class TestRendezvousIntegration:
    def test_join_backoff_shape(self):
        from pytorch_operator_tpu.runtime.rendezvous import join_backoff

        b = join_backoff(timeout_s=60.0, base_s=1.0, seed=0)
        # Base honored, cap inside the join timeout, capped at 10s.
        assert b.base_s == 1.0
        assert b.cap_s == 10.0
        assert join_backoff(timeout_s=8.0, base_s=1.0, seed=0).cap_s == 2.0

    def test_worker_seeds_decorrelate(self):
        from pytorch_operator_tpu.runtime.rendezvous import join_backoff

        w0 = join_backoff(60.0, 1.0, seed=0).delays(6)
        w1 = join_backoff(60.0, 1.0, seed=1).delays(6)
        assert w0 != w1  # no thundering herd on the coordinator
