"""Elastic preemption-recovery end-to-end: REAL subprocess gang, real
jax.distributed world, real orbax checkpoints, deterministic fault
injection.

This is the BASELINE.md "Elastic job: preemption → in-place restart" row:
a Worker dies mid-training with a retryable exit code; the supervisor
gang-restarts the world (elastic re-rendezvous) and the restarted gang
RESUMES from the latest checkpoint rather than restarting from step 0.
Reference analog: pod preemption → operator respawn → user script reloads
its checkpoint (SURVEY.md §5 "Failure detection / elastic recovery").
"""

import pathlib

from pytorch_operator_tpu.api import (
    ElasticPolicy,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    Resources,
    RestartPolicy,
)
from pytorch_operator_tpu.controller import Supervisor
from tests.testutil import new_job

import pytest

# Fast-lane exclusion (-m 'not slow'): real-subprocess elastic shrink/grow e2es.
pytestmark = pytest.mark.slow

def _llama_args(max_steps):
    """The canonical tiny-llama e2e arg list (one definition so the two
    e2e scenarios cannot drift on shared knobs)."""
    return [
        "--config", "tiny", "--seq-len", "32", "--batch-size", "4",
        "--steps", "500", "--max-steps", str(max_steps),
        "--checkpoint-every", "3", "--warmup", "1",
    ]


LLAMA_ARGS = _llama_args(30)


def _llama_template(extra_args=()):
    return ProcessTemplate(
        module="pytorch_operator_tpu.workloads.llama_train",
        args=LLAMA_ARGS + list(extra_args),
        resources=Resources(cpu_devices=1),
    )


def test_shrink_resume_reshards_checkpoint_across_world_sizes(tmp_path):
    """Elastic's headline promise end-to-end (VERDICT r2 Missing #3 /
    Weak #6): a preempted fsdp=4 world comes back SMALLER (capacity
    pressure admits only master + 1 worker), and the shrunk fsdp=2 world
    must RESUME from the fsdp=4 checkpoint — orbax resharding the saved
    state onto the new mesh — not restart from step 0.

    Life 1 (supervisor with 4 slots): master + 3 workers, real
    jax.distributed fsdp=4 training; every worker preempts at step 8
    (mass preemption — the whole slice went away) with no restart
    budget -> job fails with checkpoints at steps 3 and 6.
    Life 2 (supervisor with 2 slots — the machine came back smaller):
    the SAME job resubmitted; elastic admission launches master + 1
    worker (ElasticScaledDown), and the fsdp=2 world resumes from step 6.
    """
    state = tmp_path / "state"
    args = _llama_args(16)

    def shrink_job(workers, worker_extra=(), backoff=0):
        job = new_job(
            name="shrink-e2e",
            workers=workers,
            restart_policy=RestartPolicy.EXIT_CODE,
            backoff_limit=backoff,
            elastic=ElasticPolicy(
                min_replicas=1, max_replicas=3, max_restarts=4
            ),
        )
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.llama_train",
            args=list(args),
            resources=Resources(cpu_devices=1),
        )
        job.spec.replica_specs[ReplicaType.WORKER] = ReplicaSpec(
            replicas=workers,
            restart_policy=RestartPolicy.EXIT_CODE,
            template=ProcessTemplate(
                module="pytorch_operator_tpu.workloads.llama_train",
                args=list(args) + list(worker_extra),
                resources=Resources(cpu_devices=1),
            ),
        )
        return job

    log_dir = state / "logs"

    def master_log():
        return "\n".join(
            p.read_text() for p in sorted(log_dir.glob("*shrink-e2e-master*"))
        )

    # ---- life 1: full world, preempt, no budget -> Failed ----
    sup1 = Supervisor(state_dir=state, poll_interval=0.05, max_slots=4)
    try:
        job1 = shrink_job(workers=3, worker_extra=["--preempt-at", "8"])
        done1 = sup1.run(job1, timeout=420)
        assert not done1.is_succeeded()
        text1 = master_log()
        assert "'fsdp': 4" in text1, text1[-2000:]
        ckpts = state / "checkpoints" / "default_shrink-e2e"
        assert any(ckpts.iterdir()), "life 1 left no checkpoint"
        from pytorch_operator_tpu.controller.store import job_key

        sup1.delete_job(job_key(done1))  # no purge: checkpoints survive
    finally:
        sup1.shutdown()

    # ---- life 2: the machine came back smaller ----
    sup2 = Supervisor(state_dir=state, poll_interval=0.05, max_slots=2)
    try:
        done2 = sup2.run(shrink_job(workers=3), timeout=420)
        assert done2.is_succeeded(), [
            c.to_dict() for c in done2.status.conditions
        ]
        from pytorch_operator_tpu.controller.store import job_key

        key2 = job_key(done2)
        assert any(
            e.reason == "ElasticScaledDown" for e in sup2.events.for_job(key2)
        )
        text2 = master_log()
        # The shrunk world really is fsdp=2...
        assert "'fsdp': 2" in text2, text2[-2000:]
        # ...and it RESUMED from life 1's checkpoint (reshard 4 -> 2),
        # step preserved (>= first life's surviving checkpoint).
        resumed = [
            ln
            for ln in text2.splitlines()
            if "resumed from checkpoint" in ln
        ]
        assert resumed, text2[-2000:]
        assert all(int(ln.rsplit("step", 1)[1]) >= 3 for ln in resumed), resumed
    finally:
        sup2.shutdown()


def test_grow_back_resumes_when_capacity_frees(tmp_path):
    """The other half of capacity-adaptivity (VERDICT r3 Missing #4 /
    Next #4), end-to-end with real subprocesses: a job whose target world
    does not fit LAUNCHES SHRUNK, and when the occupying job finishes the
    reconciler grows the world back to target via _maybe_grow_elastic —
    training resuming from checkpoint across BOTH transitions.

    One supervisor, 4 slots. A squatter job holds 2 slots and exits only
    once the elastic job's first checkpoint lands (deterministic capacity
    release — no sleep tuning). The elastic job targets master+3 workers
    (4 slots): admission shrinks it to master+1 (fsdp=2,
    ElasticScaledDown); the squatter's exit frees 2 slots; grow-back
    tears the world down (ElasticScaledUp, one restart spent) and the
    fsdp=4 world resumes from the fsdp=2 checkpoint and finishes.
    """
    state = tmp_path / "state"
    args = _llama_args(16)
    sup = Supervisor(state_dir=state, poll_interval=0.05, max_slots=4)
    try:
        ckpt_glob = str(
            state / "checkpoints" / "default_grow-e2e" / "*" / "_CHECKPOINT_METADATA"
        )
        # Master-only, holding BOTH slots in one process: the capacity
        # frees atomically, so grow-back happens in ONE membership change
        # (two 1-slot replicas exiting across sync passes would grow the
        # world twice, spending two restarts — legal, but nondeterministic).
        squatter = new_job(name="squatter", workers=0)
        squatter.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="tests.standby_probe",
            env={"PROBE_WAIT_FOR_GLOB": ckpt_glob},
            resources=Resources(cpu_devices=2),
        )
        squat_key = sup.submit(squatter)
        # wait() reconciles only the named job, so the squatter needs its
        # own reconcile pump (the daemon-loop analog) for the duration.
        import threading
        import time as _time

        stop_pump = threading.Event()

        def pump():
            while not stop_pump.is_set():
                try:
                    sup.reconciler.sync(squat_key)
                except Exception:
                    return
                _time.sleep(0.05)

        pump_t = threading.Thread(target=pump, daemon=True)
        pump_t.start()
        # The squatter must actually HOLD its 2 slots before the elastic
        # job is admitted, or both fit and no shrink happens.
        deadline = _time.time() + 60
        while (
            sum(
                e.reason == "SuccessfulCreateReplica"
                for e in sup.events.for_job(squat_key)
            )
            < 1
        ):
            assert _time.time() < deadline, "squatter never launched"
            _time.sleep(0.05)

        job = new_job(
            name="grow-e2e",
            workers=3,
            restart_policy=RestartPolicy.EXIT_CODE,
            backoff_limit=4,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=3, max_restarts=4),
        )
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.llama_train",
            args=list(args),
            resources=Resources(cpu_devices=1),
        )
        job.spec.replica_specs[ReplicaType.WORKER] = ReplicaSpec(
            replicas=3,
            restart_policy=RestartPolicy.EXIT_CODE,
            template=ProcessTemplate(
                module="pytorch_operator_tpu.workloads.llama_train",
                args=list(args),
                resources=Resources(cpu_devices=1),
            ),
        )
        key = sup.submit(job)
        done = sup.wait(key, timeout=420)
        assert done.is_succeeded(), [c.to_dict() for c in done.status.conditions]
        squat_done = sup.wait(squat_key, timeout=60)
        assert squat_done.is_succeeded()
        stop_pump.set()
        pump_t.join(timeout=10)

        reasons = [e.reason for e in sup.events.for_job(key)]
        assert "ElasticScaledDown" in reasons, reasons
        assert "ElasticScaledUp" in reasons, reasons
        # The grow-back is a membership change: exactly one restart spent.
        assert done.status.restart_count == 1

        text = "\n".join(
            p.read_text()
            for p in sorted((state / "logs").glob("*grow-e2e-master*"))
        )
        # Life 1 really ran shrunk, life 2 at the full target world.
        assert "'fsdp': 2" in text, text[-2000:]
        assert "'fsdp': 4" in text, text[-2000:]
        # And life 2 resumed from life 1's checkpoint, not step 0 —
        # step/loss continuity across the grow transition.
        resumed = [
            ln for ln in text.splitlines() if "resumed from checkpoint" in ln
        ]
        assert resumed, text[-2000:]
        assert all(int(ln.rsplit("step", 1)[1]) >= 3 for ln in resumed), resumed
    finally:
        sup.shutdown()


def test_preemption_gang_restart_resumes_from_checkpoint(tmp_path):
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.05)
    job = new_job(
        name="elastic-e2e",
        workers=1,
        restart_policy=RestartPolicy.EXIT_CODE,
        backoff_limit=4,
        elastic=ElasticPolicy(min_replicas=1, max_replicas=2, max_restarts=4),
    )
    job.spec.replica_specs[ReplicaType.MASTER].template = _llama_template()
    # The Worker preempts itself at step 12 of its FIRST life (restart
    # count 0): checkpoints exist at steps 3..12 by then, so the restarted
    # gang must resume from step >= 9, not from 0.
    job.spec.replica_specs[ReplicaType.WORKER] = ReplicaSpec(
        replicas=1,
        restart_policy=RestartPolicy.EXIT_CODE,
        template=_llama_template(["--preempt-at", "12"]),
    )
    try:
        done = sup.run(job, timeout=420)
        assert done.is_succeeded(), [c.to_dict() for c in done.status.conditions]
        assert done.status.restart_count == 1

        logs = sorted((tmp_path / "state" / "logs").glob("*elastic-e2e*"))
        text = "\n".join(p.read_text() for p in logs)
        assert "injected preemption at step" in text
        # The resumed life picked up a checkpoint at a nonzero step.
        resumed = [
            ln for ln in text.splitlines() if "resumed from checkpoint" in ln
        ]
        assert resumed, text[-2000:]
        steps = [int(ln.rsplit("step", 1)[1]) for ln in resumed]
        assert all(s >= 3 for s in steps), resumed
    finally:
        sup.shutdown()
