"""Elastic preemption-recovery end-to-end: REAL subprocess gang, real
jax.distributed world, real orbax checkpoints, deterministic fault
injection.

This is the BASELINE.md "Elastic job: preemption → in-place restart" row:
a Worker dies mid-training with a retryable exit code; the supervisor
gang-restarts the world (elastic re-rendezvous) and the restarted gang
RESUMES from the latest checkpoint rather than restarting from step 0.
Reference analog: pod preemption → operator respawn → user script reloads
its checkpoint (SURVEY.md §5 "Failure detection / elastic recovery").
"""

import pathlib

from pytorch_operator_tpu.api import (
    ElasticPolicy,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    Resources,
    RestartPolicy,
)
from pytorch_operator_tpu.controller import Supervisor
from tests.testutil import new_job

LLAMA_ARGS = [
    "--config", "tiny", "--seq-len", "32", "--batch-size", "4",
    "--steps", "500", "--max-steps", "30", "--checkpoint-every", "3",
    "--warmup", "1",
]


def _llama_template(extra_args=()):
    return ProcessTemplate(
        module="pytorch_operator_tpu.workloads.llama_train",
        args=LLAMA_ARGS + list(extra_args),
        resources=Resources(cpu_devices=1),
    )


def test_preemption_gang_restart_resumes_from_checkpoint(tmp_path):
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.05)
    job = new_job(
        name="elastic-e2e",
        workers=1,
        restart_policy=RestartPolicy.EXIT_CODE,
        backoff_limit=4,
        elastic=ElasticPolicy(min_replicas=1, max_replicas=2, max_restarts=4),
    )
    job.spec.replica_specs[ReplicaType.MASTER].template = _llama_template()
    # The Worker preempts itself at step 12 of its FIRST life (restart
    # count 0): checkpoints exist at steps 3..12 by then, so the restarted
    # gang must resume from step >= 9, not from 0.
    job.spec.replica_specs[ReplicaType.WORKER] = ReplicaSpec(
        replicas=1,
        restart_policy=RestartPolicy.EXIT_CODE,
        template=_llama_template(["--preempt-at", "12"]),
    )
    try:
        done = sup.run(job, timeout=420)
        assert done.is_succeeded(), [c.to_dict() for c in done.status.conditions]
        assert done.status.restart_count == 1

        logs = sorted((tmp_path / "state" / "logs").glob("*elastic-e2e*"))
        text = "\n".join(p.read_text() for p in logs)
        assert "injected preemption at step" in text
        # The resumed life picked up a checkpoint at a nonzero step.
        resumed = [
            ln for ln in text.splitlines() if "resumed from checkpoint" in ln
        ]
        assert resumed, text[-2000:]
        steps = [int(ln.rsplit("step", 1)[1]) for ln in resumed]
        assert all(s >= 3 for s in steps), resumed
    finally:
        sup.shutdown()
