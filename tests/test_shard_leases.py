"""Shard-lease races, rebalance, failover, and the steady-pool autoscaler.

Grown from tests/test_store_cache.py::TestMarkerExactlyOnce: the same
two-supervisors-one-dir discipline, applied to the job-space leases the
sharded control plane runs on (controller/leases.py). The contracts
under test:

- renewal-vs-expiry interleavings: a renew after expiry NEVER quietly
  overwrites a stealer; it goes through the contended acquire path;
- fencing: a stale holder's writes are rejected once a rival bumped the
  token (drop_lease / partition scenarios);
- simultaneous claim by two joiners is exactly-once (O_EXCL claim file);
- drain-then-rejoin rebalances within a tick, death within one TTL;
- the chaos-driven failover e2e: kill one of two supervisors mid-pass,
  the orphaned shards are re-claimed within one lease TTL, and no job
  ends up with two live worlds.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from pytorch_operator_tpu.controller.autoscale import PoolAutoscaler
from pytorch_operator_tpu.controller.leases import (
    ShardLease,
    ShardManager,
    read_shard_config,
    read_shard_owners,
    shard_of_key,
)

T0 = 1_000_000.0  # synthetic clock origin — no wall-clock in the units


def lease(tmp_path, shard=0, who="a", ttl=5.0):
    d = tmp_path / "leases"
    d.mkdir(parents=True, exist_ok=True)
    return ShardLease(d, shard, who, ttl=ttl)


def manager(tmp_path, who, shards=4, ttl=5.0):
    # auto_renew=False: the units drive tick(now) on a synthetic clock;
    # a real-time renewal thread would fight the test's sense of time.
    return ShardManager(
        tmp_path, shards, identity=who, ttl=ttl, auto_renew=False
    )


class TestShardLease:
    def test_claim_free_shard_starts_token_at_one(self, tmp_path):
        a = lease(tmp_path, who="a")
        assert a.try_acquire(T0)
        assert a.token == 1
        rec = json.loads(a.path.read_text())
        assert rec["holder"] == "a"
        assert rec["token"] == 1
        assert rec["expires"] == pytest.approx(T0 + 5.0)

    def test_validly_held_shard_rejects_a_rival(self, tmp_path):
        a, b = lease(tmp_path, who="a"), lease(tmp_path, who="b")
        assert a.try_acquire(T0)
        assert not b.try_acquire(T0 + 1.0)
        assert b.token == 0

    def test_renewal_extends_without_bumping_the_token(self, tmp_path):
        a = lease(tmp_path, who="a")
        a.try_acquire(T0)
        assert a.renew(T0 + 2.0)
        assert a.token == 1
        assert a.expires == pytest.approx(T0 + 7.0)

    def test_renew_after_expiry_is_refused_not_overwriting(self, tmp_path):
        """THE renewal-vs-expiry interleaving: once its lease expired,
        a holder may not renew-in-place (a stealer may already own the
        path) — it must drop and re-contend."""
        a = lease(tmp_path, who="a")
        a.try_acquire(T0)
        assert not a.renew(T0 + 6.0)  # ttl=5: expired
        assert a.token == 0

    def test_steal_of_expired_lease_bumps_fencing_token(self, tmp_path):
        a, b = lease(tmp_path, who="a"), lease(tmp_path, who="b")
        a.try_acquire(T0)
        assert b.try_acquire(T0 + 6.0)  # expired -> stealable
        assert b.token == 2
        assert b.takeover_from == "a"

    def test_fencing_rejects_stale_holders_write(self, tmp_path):
        """drop_lease scenario: the on-disk lease is force-expired under
        a live holder; a rival claims (token+1); the stale holder's
        next renew must be REJECTED and must not clobber the rival."""
        a, b = lease(tmp_path, who="a"), lease(tmp_path, who="b")
        a.try_acquire(T0)
        a.force_expire()  # disk says expired; a's memory says held
        assert b.try_acquire(T0 + 0.1)
        assert b.token == 2
        # a still believes it holds (in-memory unexpired) — the write
        # path must notice the token moved.
        assert not a.renew(T0 + 1.0)
        assert a.token == 0
        rec = json.loads(b.path.read_text())
        assert (rec["holder"], rec["token"]) == ("b", 2)

    def test_simultaneous_claim_by_two_joiners_exactly_once(self, tmp_path):
        """Two joiners race try_acquire on a free shard; the O_EXCL
        claim file hands it to exactly one — every round."""
        for round_ in range(10):
            a = lease(tmp_path, shard=round_, who="a")
            b = lease(tmp_path, shard=round_, who="b")
            results = {}
            barrier = threading.Barrier(2)

            def claim(lz, tag):
                barrier.wait()
                results[tag] = lz.try_acquire(T0)

            ts = [
                threading.Thread(target=claim, args=(a, "a")),
                threading.Thread(target=claim, args=(b, "b")),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(10)
            assert sorted(results.values()) == [False, True], results

    def test_release_keeps_the_token_monotonic(self, tmp_path):
        a, b = lease(tmp_path, who="a"), lease(tmp_path, who="b")
        a.try_acquire(T0)
        a.release(T0 + 1.0)
        assert b.try_acquire(T0 + 1.1)  # released -> immediately claimable
        assert b.token == 2  # monotonic across release->claim
        assert b.takeover_from is None  # voluntary hand-back, not a death

    def test_own_surviving_lease_reattaches_on_restart(self, tmp_path):
        a = lease(tmp_path, who="a")
        a.try_acquire(T0)
        a2 = lease(tmp_path, who="a")  # same identity, fresh process
        assert a2.try_acquire(T0 + 1.0)
        assert a2.token == 1  # reattached, no ownership change


class TestShardManager:
    def test_single_manager_claims_every_shard(self, tmp_path):
        a = manager(tmp_path, "a")
        changes = a.tick(T0)
        assert sorted(changes["acquired"]) == [0, 1, 2, 3]
        assert a.owns_key("default/x", T0 + 1.0)

    def test_two_managers_split_disjoint_and_complete(self, tmp_path):
        a, b = manager(tmp_path, "a"), manager(tmp_path, "b")
        # Interleave ticks until stable (presence discovery -> release
        # -> claim takes a few rounds).
        for i in range(6):
            a.tick(T0 + i * 0.1)
            b.tick(T0 + i * 0.1)
        assert a.owned | b.owned == {0, 1, 2, 3}
        assert not (a.owned & b.owned)
        assert len(a.owned) == len(b.owned) == 2

    def test_join_rebalances_within_one_ttl(self, tmp_path):
        a = manager(tmp_path, "a", ttl=5.0)
        a.tick(T0)
        assert len(a.owned) == 4
        b = manager(tmp_path, "b", ttl=5.0)
        # Everything below happens within ONE ttl of synthetic time.
        b.tick(T0 + 0.1)  # announces presence; nothing claimable yet
        a.tick(T0 + 0.2)  # sees b -> releases down to fair share
        changes = b.tick(T0 + 0.3)  # claims the released shards
        assert len(changes["acquired"]) == 2
        assert a.owned | b.owned == {0, 1, 2, 3}
        assert not (a.owned & b.owned)

    def test_supervisor_death_fails_over_within_one_ttl(self, tmp_path):
        ttl = 5.0
        a, b = manager(tmp_path, "a", ttl=ttl), manager(tmp_path, "b", ttl=ttl)
        for i in range(6):
            a.tick(T0 + i * 0.1)
            b.tick(T0 + i * 0.1)
        dead = set(a.owned)
        # a dies at T0+1: stops ticking/renewing. b keeps ticking (its
        # own leases stay renewed); by T0+1+ttl a's leases are
        # stealable — the orphan rescue claims them on b's next tick,
        # within one TTL of a's last renewal.
        b.tick(T0 + 2.0)
        b.tick(T0 + 4.0)
        assert len(b.owned) == 2  # nothing stealable yet
        t_rec = T0 + 1.0 + ttl + 0.1
        changes = b.tick(t_rec)
        assert set(changes["acquired"]) == dead
        assert b.owned == {0, 1, 2, 3}

    def test_drain_then_rejoin(self, tmp_path):
        a, b = manager(tmp_path, "a"), manager(tmp_path, "b")
        for i in range(6):
            a.tick(T0 + i * 0.1)
            b.tick(T0 + i * 0.1)
        released = b.drain(T0 + 1.0)
        assert released and not b.owned
        # a picks the drained shards up immediately (released, not
        # expired — no TTL wait).
        a.tick(T0 + 1.1)
        assert a.owned == {0, 1, 2, 3}
        # rejoin: a fresh manager with the same identity rebalances back.
        b2 = manager(tmp_path, "b")
        b2.tick(T0 + 2.0)
        a.tick(T0 + 2.1)
        b2.tick(T0 + 2.2)
        assert a.owned | b2.owned == {0, 1, 2, 3}
        assert not (a.owned & b2.owned)
        assert len(b2.owned) == 2

    def test_lost_lease_surfaces_through_tick(self, tmp_path):
        a = manager(tmp_path, "a", ttl=5.0)
        a.tick(T0)
        # Force-expire everything on disk (the drop_lease fault), let a
        # rival steal one, then tick a at renew time: losses reported.
        a.inject_drop("*")
        b = manager(tmp_path, "b", ttl=5.0)
        b.tick(T0 + 0.5)
        changes = a.tick(T0 + 3.0)  # past ttl/2: renewal due -> fencing
        assert changes["lost"], changes
        assert not (a.owned & b.owned)

    def test_shard_count_mismatch_is_rejected(self, tmp_path):
        manager(tmp_path, "a", shards=4)
        with pytest.raises(ValueError, match="sharded 4 ways"):
            manager(tmp_path, "b", shards=8)

    def test_observer_helpers_read_config_and_owners(self, tmp_path):
        a = manager(tmp_path, "a")
        a.tick(T0)
        assert read_shard_config(tmp_path) == 4
        # Owners are judged against the REAL clock; re-acquire with
        # real time so the observer sees live leases.
        for i in list(a.owned):
            a.leases[i].release(time.time())
        a.owned.clear()
        a.tick(time.time())
        owners = read_shard_owners(tmp_path)
        assert set(owners.values()) == {"a"}

    def test_spec_pin_overrides_the_hash(self):
        assert shard_of_key("default/j", 8, pin=13) == 13 % 8
        assert 0 <= shard_of_key("default/j", 8) < 8


class TestPoolAutoscaler:
    def test_grows_on_latency_and_respects_ceiling(self):
        s = PoolAutoscaler(floor=2, ceiling=16, target_s=0.1)
        # 2 workers took 1.6s over plenty of jobs -> work = 3.2s ->
        # wants 32, clamped to ceiling.
        assert s.observe(1.6, 5000) == 16
        for _ in range(50):
            assert s.observe(10.0, 5000) <= 16

    def test_shrinks_to_floor_on_an_idle_fleet(self):
        s = PoolAutoscaler(floor=2, ceiling=16, target_s=0.1, shrink_patience=3)
        s.observe(1.6, 5000)
        assert s.size == 16
        for _ in range(30):
            s.observe(0.0, 0)
        assert s.size == s.floor

    def test_shrink_has_hysteresis(self):
        s = PoolAutoscaler(floor=2, ceiling=16, target_s=0.1, shrink_patience=4)
        s.observe(1.6, 5000)
        for _ in range(3):
            s.observe(0.0, 0)
        assert s.size == 16  # patience not yet exhausted
        s.observe(0.0, 0)
        assert s.size < 16  # halving begins

    def test_never_more_workers_than_jobs(self):
        s = PoolAutoscaler(floor=2, ceiling=16, target_s=0.1)
        assert s.observe(5.0, 3) <= 3

    def test_fixed_mode_is_inert(self):
        s = PoolAutoscaler(floor=8, ceiling=8)
        assert s.fixed
        assert s.observe(100.0, 10000) == 8
        assert s.observe(0.0, 0) == 8


def _mk_sups(tmp_path, n=2, shards=4, ttl=1.0):
    from pytorch_operator_tpu.controller.runner import FakeRunner
    from pytorch_operator_tpu.controller.supervisor import Supervisor

    sups = []
    for i in range(n):
        sup = Supervisor(
            state_dir=tmp_path,
            runner=FakeRunner(),
            persist=True,
            shards=shards,
            supervisor_id=f"sup-{chr(ord('a') + i)}",
            lease_ttl=ttl,
            sync_workers_max=8,
        )
        sup.fault_kill_action = sup.simulate_crash
        sups.append(sup)
    return sups


def _pass(sup):
    sup.store.rescan()
    sup.process_deletion_markers()
    sup.process_scale_markers()
    sup.process_suspend_markers()
    sup.process_apply_markers()
    sup.sync_once()


def _settle(sups, shards, deadline_s=10.0):
    deadline = time.time() + deadline_s
    while time.time() < deadline:
        for sup in sups:
            _pass(sup)
        owned = [len(sup.shards.owned) for sup in sups]
        if sum(owned) == shards and all(n > 0 for n in owned):
            return
        time.sleep(0.02)
    raise AssertionError(f"shards never settled: {owned}")


def _active_owners(sups):
    owners = {}
    for sup in sups:
        for h in sup.runner.list_all():
            if h.is_active():
                owners.setdefault(h.job_key, set()).add(sup.identity)
    return owners


class TestShardFailoverE2E:
    def test_kill_supervisor_fault_fails_over_within_one_ttl(self, tmp_path):
        """The chaos acceptance: two supervisors split the job space; a
        kill_supervisor fault takes one down mid-run; the orphaned
        shards are re-claimed within one lease TTL and no job is
        double-spawned (one live world per job throughout)."""
        from pytorch_operator_tpu import faults
        from pytorch_operator_tpu.controller.supervisor import (
            SupervisorKilledError,
        )
        from pytorch_operator_tpu.faults.plan import Fault, FaultPlan
        from tests.testutil import new_job

        ttl = 1.0
        sups = _mk_sups(tmp_path, ttl=ttl)
        a, b = sups
        try:
            _settle(sups, 4)
            for i in range(12):
                a.submit(new_job(name=f"fo-{i}"))
            for _ in range(3):
                for sup in sups:
                    _pass(sup)
            before = _active_owners(sups)
            assert len(before) == 12
            assert all(len(v) == 1 for v in before.values())
            victims = {k for k, v in before.items() if v == {"sup-a"}}
            assert victims  # the split gave sup-a some jobs

            # Chaos-drivable: the kill is DECLARED, not hand-rolled.
            faults.arm(
                FaultPlan(
                    faults=[Fault(kind="kill_supervisor", target="sup-a", at=1)]
                )
            )
            try:
                with pytest.raises(SupervisorKilledError):
                    _pass(a)  # dies mid-pass; leases left to expire
            finally:
                faults.disarm()
            t_dead = time.time()

            # Only b survives. Its next passes must re-claim a's shards
            # as they expire — within one TTL — and re-create exactly
            # the orphaned worlds.
            deadline = t_dead + ttl + 1.0
            while time.time() < deadline and len(b.shards.owned) < 4:
                _pass(b)
                time.sleep(0.05)
            t_recovered = time.time()
            assert b.shards.owned == {0, 1, 2, 3}
            # The failover bound: orphaned shards re-claimed within one
            # lease TTL (plus one pass of slack for the tick cadence).
            assert t_recovered - t_dead <= ttl + 1.0
            for _ in range(3):
                _pass(b)
            # Every job has exactly one LIVE world again, all owned by
            # the survivor; the victims were re-spawned by b, not
            # duplicated (a is dead — only b's runner is live).
            after = _active_owners([b])
            assert set(after) == set(before)
            assert all(v == {"sup-b"} for v in after.values())
            # The hand-off is on the record: the acquisition events name
            # the dead holder, so `tpujob why` can cite the ownership
            # change and `tpujob chaos --record` can reconstruct it.
            from pytorch_operator_tpu.controller.leases import SHARD_EVENT_KEY

            msgs = [
                e.message
                for e in b.events.for_job(SHARD_EVENT_KEY)
                if e.reason == "ShardAcquired"
            ]
            assert any("after lease expiry of sup-a" in m for m in msgs)
            # ...and `tpujob chaos --record` reconstructs the incident
            # as a replayable kill_supervisor fault from those events.
            from pytorch_operator_tpu.faults.record import plan_from_recording

            victim_key = sorted(victims)[0]
            plan = plan_from_recording(tmp_path, victim_key)
            kills = [f for f in plan.faults if f.kind == "kill_supervisor"]
            assert kills and kills[0].target == "sup-a"
        finally:
            for sup in sups:
                try:
                    sup.shutdown()
                except Exception:
                    pass

    def test_drop_lease_fault_fences_the_stale_holder(self, tmp_path):
        """drop_lease chaos: the holder's on-disk lease is force-expired
        mid-run; the rival claims it and the stale holder's next renew
        is fencing-rejected (ShardLeaseLost) — converging back to one
        owner per shard with every world singly-owned."""
        from pytorch_operator_tpu import faults
        from pytorch_operator_tpu.faults.plan import Fault, FaultPlan
        from tests.testutil import new_job

        ttl = 0.6
        sups = _mk_sups(tmp_path, ttl=ttl)
        a, b = sups
        try:
            _settle(sups, 4)
            for i in range(8):
                a.submit(new_job(name=f"dl-{i}"))
            for _ in range(3):
                for sup in sups:
                    _pass(sup)
            target = sorted(a.shards.owned)[0]
            faults.arm(
                FaultPlan(
                    faults=[Fault(kind="drop_lease", target=str(target), at=1)]
                )
            )
            try:
                _pass(a)  # drops its own lease on disk, keeps believing
            finally:
                faults.disarm()
            # Run both until a's stale hold is fencing-rejected (its
            # renew reads the force-expired/stolen record and drops) —
            # within ~half a TTL. WHO ends up owning the shard is
            # legitimately either of them (a may re-claim the orphan it
            # just lost); the contract is the rejection plus
            # convergence back to exactly one owner.
            deadline = time.time() + 4 * ttl + 2.0
            while time.time() < deadline:
                _pass(a)
                _pass(b)
                if a.metrics.shard_losses.get() >= 1:
                    break
                time.sleep(0.05)
            assert a.metrics.shard_losses.get() >= 1
            assert any(
                e.reason == "ShardLeaseLost"
                for e in a.events.for_job(
                    __import__(
                        "pytorch_operator_tpu.controller.leases",
                        fromlist=["SHARD_EVENT_KEY"],
                    ).SHARD_EVENT_KEY
                )
            )
            # Settle: exactly one owner per shard, one world per job.
            deadline = time.time() + 4 * ttl + 2.0
            while time.time() < deadline:
                _pass(a)
                _pass(b)
                if (
                    a.shards.owned | b.shards.owned == {0, 1, 2, 3}
                    and not (a.shards.owned & b.shards.owned)
                ):
                    break
                time.sleep(0.05)
            assert a.shards.owned | b.shards.owned == {0, 1, 2, 3}
            assert not (a.shards.owned & b.shards.owned)
            for _ in range(3):
                _pass(a)
                _pass(b)
            owners = _active_owners(sups)
            assert all(len(v) == 1 for v in owners.values()), owners
        finally:
            for sup in sups:
                try:
                    sup.shutdown()
                except Exception:
                    pass


class TestSteadyFastPath:
    """The fast path must be invisible: anything that CAN change a
    steady job still reconciles it."""

    def _steady_sup(self, tmp_path):
        from pytorch_operator_tpu.api.types import ReplicaPhase
        from pytorch_operator_tpu.controller.runner import FakeRunner
        from pytorch_operator_tpu.controller.supervisor import Supervisor
        from tests.testutil import new_job

        sup = Supervisor(state_dir=tmp_path, runner=FakeRunner())
        key = sup.submit(new_job(name="steady"))
        sup.sync_once()
        for h in sup.runner.list_all():
            sup.runner.set_phase(h.name, ReplicaPhase.RUNNING)
        sup.sync_once()  # observes RUNNING
        sup.sync_once()  # steady reconcile -> arms the fast path
        return sup, key

    def test_idle_passes_are_fast_skipped(self, tmp_path):
        sup, _ = self._steady_sup(tmp_path)
        base = sup.metrics.steady_fast_skips.get()
        sup.sync_once()
        sup.sync_once()
        assert sup.metrics.steady_fast_skips.get() >= base + 2
        sup.shutdown()

    def test_replica_exit_breaks_the_skip(self, tmp_path):
        from pytorch_operator_tpu.api.types import ReplicaPhase

        sup, key = self._steady_sup(tmp_path)
        sup.sync_once()  # skipping now
        for h in sup.runner.list_for_job(key):
            sup.runner.set_phase(h.name, ReplicaPhase.SUCCEEDED, exit_code=0)
        sup.sync_once()
        assert sup.get(key).is_succeeded()
        sup.shutdown()

    def test_direct_suspend_mutation_still_acts(self, tmp_path):
        # The touch()-exempt field: flipped in place without bumping the
        # generation (tests/test_suspend.py relies on this).
        sup, key = self._steady_sup(tmp_path)
        sup.sync_once()
        j = sup.get(key)
        j.spec.run_policy.suspend = True
        sup.store.update(j)
        sup.sync_once()
        assert sup.runner.list_for_job(key) == []
        sup.shutdown()

    def test_first_status_record_is_noticed(self, tmp_path):
        """A job that never reported gets its status dir scans
        throttled; the FIRST replica file must still be noticed within
        the stagger window (4 passes) and folded into the gauges."""
        import json as _json

        from pytorch_operator_tpu.controller.progress import job_status_dir

        sup, key = self._steady_sup(tmp_path)
        for _ in range(6):
            sup.sync_once()  # throttle engages on the empty dir
        d = job_status_dir(sup.reconciler.status_root, key)
        d.mkdir(parents=True, exist_ok=True)
        (d / "master-0.jsonl").write_text(
            _json.dumps(
                {"event": "progress", "ts": time.time(), "step": 7,
                 "steps_per_sec": 2.0}
            )
            + "\n"
        )
        for _ in range(5):  # >= the 4-pass stagger window
            sup.sync_once()
        assert sup.metrics.job_step.get(job=key) == 7.0
        sup.shutdown()
