"""Multi-host realism: N processes × M devices each, one global mesh.

The reference's multi-node story is N pods × M GPUs with NCCL spanning
them (SURVEY.md §2 "Comm backend"). TPU-native, a "host" is a process
owning several local chips and the global mesh spans all processes, with
the cross-host axis marked ``@dcn`` so only bandwidth-light collectives
(data-parallel gradient psums) cross the slow network (parallel/mesh.py
``make_hybrid_mesh``; dcn axes outermost).

The existing smoke/elastic e2es run N processes × 1 device. This is the
missing shape: the supervisor gang-launches 2 processes that each hold 4
forced-CPU devices, rendezvous into ONE 8-device world, and train the
flagship LM on a hybrid dp(across hosts)×fsdp(within host) mesh. The
final loss must match a single-process 8-device run of the same global
batch — sharding layout and process topology must not change numerics.

Marked slow: two jax imports + gloo setup + CPU training.
"""

import re

import pytest

import tests.jaxenv  # noqa: F401  (CPU platform, 8 local devices)
from pytorch_operator_tpu.api import ProcessTemplate, ReplicaType, Resources
from pytorch_operator_tpu.controller import Supervisor
from pytorch_operator_tpu.workloads import llama_train
from tests.testutil import new_job

ARGS = [
    "--config", "tiny",
    "--seq-len", "32",
    "--batch-size", "4",
    "--steps", "6",
    "--warmup", "1",
]


@pytest.mark.slow
def test_two_hosts_four_devices_each_train_one_hybrid_mesh(tmp_path):
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.1)
    job = new_job(name="multihost", workers=1)
    job.spec.port = None  # auto-allocate: avoid TIME_WAIT across test runs
    for rs in job.spec.replica_specs.values():
        rs.template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.llama_train",
            args=ARGS + ["--mesh", "dp=2@dcn,fsdp=4"],
            resources=Resources(cpu_devices=4),
        )
    done = sup.run(job, timeout=300)
    logs = {
        role: (
            tmp_path / "state" / "logs" / f"default_multihost-{role}-0.log"
        ).read_text()
        for role in ("master", "worker")
    }
    assert done.is_succeeded(), f"master:\n{logs['master']}\nworker:\n{logs['worker']}"
    sup.shutdown()

    # One world: every process sees all 8 devices and the hybrid mesh.
    assert "mesh={'dp': 2, 'fsdp': 4}" in logs["master"], logs["master"]
    m = re.search(r"final loss (\d+\.\d+)", logs["master"])
    assert m, logs["master"]
    multihost_loss = float(m.group(1))

    # Numerics pin: the same global batch on a single-process 8-device
    # mesh must land on the same loss (reduction-order tolerance only).
    ref = llama_train.run(
        config="tiny",
        mesh_spec="dp=2,fsdp=4",
        batch_size=4,
        seq_len=32,
        steps=6,
        warmup=1,
        log=lambda *a, **k: None,
    )
    assert multihost_loss == pytest.approx(ref["final_loss"], abs=2e-3), (
        multihost_loss,
        ref["final_loss"],
    )
