"""Flash-attention kernel tests: forward and backward against the dense
XLA oracle, on the CPU backend in pallas interpret mode (the same kernel
code compiles on real TPU; shapes here are chosen to exercise multiple
grid steps, causal block skipping, and GQA index mapping)."""

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401

from pytorch_operator_tpu.ops.flash_attention import (
    _dense_reference,
    flash_attention,
)


def _rand_qkv(key, B, S, H, KH, D, dtype):
    import jax

    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KH, D), dtype)
    v = jax.random.normal(kv, (B, S, KH, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2), (8, 2)])
def test_forward_matches_dense(causal, H, KH):
    import jax

    B, S, D = 2, 64, 16
    q, k, v = _rand_qkv(jax.random.key(0), B, S, H, KH, D, np.float32)
    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    ref = _dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_unaligned_seq_pads_and_masks(causal):
    """S not divisible by the blocks is zero-padded to alignment with the
    kernel's kv_len mask hiding the padded key columns (round 4 —
    previously these shapes fell back to the dense O(S^2) path). The
    ViT-shaped case: S=100 padded to 128."""
    import jax

    q, k, v = _rand_qkv(jax.random.key(3), 1, 100, 2, 2, 16, np.float32)
    out = flash_attention(
        q, k, v, causal=causal, block_q=64, block_k=64, interpret=True
    )
    ref = _dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_tiling_plan_tpu_alignment():
    """The real-TPU tiling plan (pure arithmetic, checkable on CPU):
    aligned shapes pass through untouched; unaligned ones pad to the
    Mosaic minima (q-blocks %8, k-blocks and D %128)."""
    from pytorch_operator_tpu.ops.flash_attention import _plan_tiling

    # The production LM shape: untouched (fast path preserved).
    assert _plan_tiling(4096, 128, 1024, 1024, False) == (1024, 1024, 4096, 128)
    # ViT-B @224: S=197 -> one 256 block; D=64 -> 128 lanes.
    assert _plan_tiling(197, 64, 1024, 1024, False) == (256, 256, 256, 128)
    # Long unaligned S keeps the swept 1024 blocks, pads S up to them.
    assert _plan_tiling(5000, 128, 1024, 1024, False) == (1024, 1024, 5120, 128)
    # User blocks below the minima are bumped, not rejected.
    assert _plan_tiling(64, 8, 4, 32, False) == (8, 128, 128, 128)
    # Unequal blocks where neither divides the other collapse to the
    # smaller size instead of padding S to their lcm (6144 here).
    assert _plan_tiling(4096, 128, 1024, 1536, False) == (1024, 1024, 4096, 128)
    # Interpret mode: no alignment minima, only S % block == 0.
    assert _plan_tiling(48, 8, 32, 32, True) == (32, 32, 64, 8)
    assert _plan_tiling(17, 8, 1024, 1024, True) == (17, 17, 17, 8)


def test_kv_len_masks_tail_keys():
    """Explicit kv_len: keys/values at positions >= kv_len must not
    contribute — equals the dense oracle run on the truncated K/V."""
    import jax
    import jax.numpy as jnp

    B, S, H, KH, D, L = 1, 64, 2, 2, 16, 37
    q, k, v = _rand_qkv(jax.random.key(6), B, S, H, KH, D, np.float32)
    out = flash_attention(
        q, k, v, causal=False, kv_len=L, block_q=16, block_k=16,
        interpret=True,
    )
    # Oracle: dense attention over the first L keys only.
    s = jnp.einsum("bshd,bthd->bhst", q, k[:, :L]) / np.sqrt(D)
    p = jax.nn.softmax(s, axis=-1)
    ref = jnp.einsum("bhst,bthd->bshd", p, v[:, :L])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_uneven_blocks():
    """block_q != block_k exercises the rectangular diagonal masking."""
    import jax

    q, k, v = _rand_qkv(jax.random.key(1), 1, 64, 2, 2, 8, np.float32)
    out = flash_attention(q, k, v, block_q=32, block_k=16, interpret=True)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    out = flash_attention(q, k, v, block_q=16, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2)])
def test_grads_match_dense(H, KH):
    import jax
    import jax.numpy as jnp

    B, S, D = 1, 32, 8
    q, k, v = _rand_qkv(jax.random.key(2), B, S, H, KH, D, np.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, block_q=8, block_k=8, interpret=True)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape)))

    def loss_dense(q, k, v):
        o = _dense_reference(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=5e-4, err_msg=f"d{name}"
        )


def test_padded_path_grads_match_dense():
    """Gradients THROUGH the padded path (S=48 padded to 64): the pad /
    slice pair must be transparent to autodiff and the kv_len mask must
    zero padded-key contributions in dq/dk/dv."""
    import jax
    import jax.numpy as jnp

    q, k, v = _rand_qkv(jax.random.key(3), 1, 48, 2, 2, 8, np.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape)))

    def loss_dense(q, k, v):
        o = _dense_reference(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape)))

    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=5e-4, err_msg=f"d{name}"
        )


def test_sharded_under_mesh():
    """mesh= wraps the kernel in shard_map over dp/tp; numerics unchanged."""
    import jax

    from pytorch_operator_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    B, S, H, KH, D = 4, 32, 4, 2, 8
    q, k, v = _rand_qkv(jax.random.key(4), B, S, H, KH, D, np.float32)

    @jax.jit
    def run(q, k, v):
        return flash_attention(
            q, k, v, block_q=16, block_k=16, mesh=mesh, interpret=True
        )

    out = run(q, k, v)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_unaligned_pads_under_mesh():
    """Padding composes with the shard_map wrapper: the pad/slice happen
    per-shard inside the manual region (S and D are unsharded axes)."""
    import jax

    from pytorch_operator_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    B, S, H, KH, D = 4, 27, 4, 2, 8  # S pads to 32 under 16-blocks
    q, k, v = _rand_qkv(jax.random.key(7), B, S, H, KH, D, np.float32)

    @jax.jit
    def run(q, k, v):
        return flash_attention(
            q, k, v, block_q=16, block_k=16, mesh=mesh, interpret=True
        )

    out = run(q, k, v)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bf16_forward_close():
    import jax
    import jax.numpy as jnp

    q, k, v = _rand_qkv(jax.random.key(5), 1, 64, 4, 2, 16, jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = _dense_reference(
        q.astype(np.float32), k.astype(np.float32), v.astype(np.float32),
        causal=True,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )
