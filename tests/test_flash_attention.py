"""Flash-attention kernel tests: forward and backward against the dense
XLA oracle, on the CPU backend in pallas interpret mode (the same kernel
code compiles on real TPU; shapes here are chosen to exercise multiple
grid steps, causal block skipping, and GQA index mapping)."""

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401

from pytorch_operator_tpu.ops.flash_attention import (
    _dense_reference,
    flash_attention,
)


def _rand_qkv(key, B, S, H, KH, D, dtype):
    import jax

    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), dtype)
    k = jax.random.normal(kk, (B, S, KH, D), dtype)
    v = jax.random.normal(kv, (B, S, KH, D), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2), (8, 2)])
def test_forward_matches_dense(causal, H, KH):
    import jax

    B, S, D = 2, 64, 16
    q, k, v = _rand_qkv(jax.random.key(0), B, S, H, KH, D, np.float32)
    out = flash_attention(
        q, k, v, causal=causal, block_q=16, block_k=16, interpret=True
    )
    ref = _dense_reference(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_unaligned_short_seq_falls_back_to_dense():
    """With interpret=False, a short sequence whose clamped blocks are not
    sublane/lane-aligned (S=100 → block_q=100) must take the dense path
    BEFORE any pallas call — so this runs fine on the CPU backend."""
    import jax

    q, k, v = _rand_qkv(jax.random.key(3), 1, 100, 2, 2, 128, np.float32)
    out = flash_attention(q, k, v, interpret=False)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_uneven_blocks():
    """block_q != block_k exercises the rectangular diagonal masking."""
    import jax

    q, k, v = _rand_qkv(jax.random.key(1), 1, 64, 2, 2, 8, np.float32)
    out = flash_attention(q, k, v, block_q=32, block_k=16, interpret=True)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
    out = flash_attention(q, k, v, block_q=16, block_k=32, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("H,KH", [(4, 4), (4, 2)])
def test_grads_match_dense(H, KH):
    import jax
    import jax.numpy as jnp

    B, S, D = 1, 32, 8
    q, k, v = _rand_qkv(jax.random.key(2), B, S, H, KH, D, np.float32)

    def loss_flash(q, k, v):
        o = flash_attention(q, k, v, block_q=8, block_k=8, interpret=True)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape)))

    def loss_dense(q, k, v):
        o = _dense_reference(q, k, v, causal=True)
        return jnp.sum(o * jnp.cos(jnp.arange(o.size).reshape(o.shape)))

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_dense = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for gf, gd, name in zip(g_flash, g_dense, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gd), atol=5e-4, err_msg=f"d{name}"
        )


def test_fallback_on_odd_shapes():
    """S not divisible by blocks → dense fallback, still correct."""
    import jax

    q, k, v = _rand_qkv(jax.random.key(3), 1, 48, 2, 2, 8, np.float32)
    out = flash_attention(q, k, v, block_q=32, block_k=32, interpret=True)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_sharded_under_mesh():
    """mesh= wraps the kernel in shard_map over dp/tp; numerics unchanged."""
    import jax

    from pytorch_operator_tpu.parallel import make_mesh

    mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
    B, S, H, KH, D = 4, 32, 4, 2, 8
    q, k, v = _rand_qkv(jax.random.key(4), B, S, H, KH, D, np.float32)

    @jax.jit
    def run(q, k, v):
        return flash_attention(
            q, k, v, block_q=16, block_k=16, mesh=mesh, interpret=True
        )

    out = run(q, k, v)
    ref = _dense_reference(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_bf16_forward_close():
    import jax
    import jax.numpy as jnp

    q, k, v = _rand_qkv(jax.random.key(5), 1, 64, 4, 2, 16, jnp.bfloat16)
    out = flash_attention(q, k, v, block_q=16, block_k=16, interpret=True)
    ref = _dense_reference(
        q.astype(np.float32), k.astype(np.float32), v.astype(np.float32),
        causal=True,
    )
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref), atol=3e-2
    )
