"""int8 quality measured through the serving path (workloads/quality_eval).

Pins the measurement machinery at tiny scale: a trained byte model's
held-out loss evaluated through chunked cache-mode decode (the serving
numerics) must beat chance and match the train-path eval closely; the
int8 variants must stay within a small delta of fp; the drift record
must cover the full generated region.
"""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401

# Fast-lane exclusion (-m 'not slow'): trains a model and runs three
# serving-path evals.
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def trained_byte_model(tmp_path_factory):
    """A tiny byte-LM trained on repo text with a checkpoint + held-out
    split (module-scoped: three tests share one training run)."""
    from pathlib import Path

    from pytorch_operator_tpu.data import pack_arrays
    from pytorch_operator_tpu.workloads import llama_train

    td = tmp_path_factory.mktemp("quality")
    data = Path("README.md").read_bytes() + Path("ARCHITECTURE.md").read_bytes()
    S = 64
    n = len(data) // S
    arr = (
        np.frombuffer(data[: n * S], np.uint8).astype(np.int32).reshape(n, S)
    )
    arr = arr[np.random.default_rng(0).permutation(n)]
    split = int(n * 0.9)
    pack_arrays(td / "train.bin", {"tokens": arr[:split]})
    pack_arrays(td / "eval.bin", {"tokens": arr[split:]})
    import os

    os.environ["TPUJOB_CHECKPOINT_DIR"] = str(td / "ckpt")
    try:
        r = llama_train.run(
            config="tiny", batch_size=16, seq_len=S, steps=40, warmup=1,
            data_file=str(td / "train.bin"), lr=3e-3, checkpoint_every=40,
            log=lambda *_: None,
        )
    finally:
        os.environ.pop("TPUJOB_CHECKPOINT_DIR", None)
    assert r["final_loss"] < 4.5  # learned past chance (ln 256 = 5.55)
    return td


def _run(td, **over):
    from pytorch_operator_tpu.workloads import quality_eval

    kw = dict(
        config="tiny", restore=str(td / "ckpt"),
        eval_file=str(td / "eval.bin"), eval_batches=1, batch_size=8,
        chunk=16, drift_tokens=96, drift_window=32, drift_prompt=16,
        log=lambda *_: None,
    )
    kw.update(over)
    return quality_eval.run(**kw)


class TestQualityEval:
    def test_serving_path_losses_and_deltas(self, trained_byte_model):
        q = _run(trained_byte_model)
        chance = np.log(256)
        # The serving-path eval must see the TRAINED model: well below
        # chance on held-out bytes.
        assert q["fp_eval_loss"] < chance - 1.0, q
        # Both sides of the quantization trade are measured, and at
        # tiny scale int8 costs (almost) nothing.
        for name in ("int8", "int8_kv8"):
            assert abs(q[f"{name}_loss_delta"]) < 0.1, q
            assert q[f"{name}_eval_argmax_agreement"] > 0.9, q

    def test_drift_covers_generated_region(self, trained_byte_model):
        q = _run(trained_byte_model)
        for name in ("int8", "int8_kv8"):
            d = q["drift"][name]
            assert d["tokens"] == 96  # the FULL generated region
            assert d["window"] == 32
            assert 0.0 <= d["overall"] <= 1.0
            assert d["first"] is not None and d["last"] is not None
            # Trained-model greedy agreement at tiny scale stays high.
            assert d["overall"] > 0.8, d

    def test_chunking_does_not_change_the_measurement(
        self, trained_byte_model
    ):
        """The serving-path loss is a property of the model, not the
        chunk size used to stream it."""
        a = _run(trained_byte_model, chunk=16)
        b = _run(trained_byte_model, chunk=64)
        assert a["fp_eval_loss"] == pytest.approx(
            b["fp_eval_loss"], abs=1e-4
        )
        assert a["int8_kv8_eval_loss"] == pytest.approx(
            b["int8_kv8_eval_loss"], abs=1e-3
        )
