"""Supervisor crash-resilience: replica records persist and live replicas
are re-adopted on restart.

Reference behavior: the operator's pods live in the API server, so a
controller restart neither kills running pods nor double-creates them —
on start the informer lists existing pods and the controller claims them
by label (SURVEY.md §3.1-3.2 "GetPodsForJob ... label-claim + adoption").
Locally: SubprocessRunner persists replica records (pid + /proc start-time
guard) under ``<state_dir>/replicas/`` and an exit-capture shell wrapper
records the exit code, so a restarted supervisor adopts live processes,
recovers exit codes of replicas that finished while it was down, and
classifies orphans that died without a record as signal deaths (137,
retryable — the preemption case).
"""

from __future__ import annotations

import json
import os
import signal
import time
from pathlib import Path

import pytest

from pytorch_operator_tpu.api.types import ProcessTemplate, ReplicaPhase, ReplicaType
from pytorch_operator_tpu.controller.runner import SubprocessRunner, replica_name
from pytorch_operator_tpu.controller.supervisor import Supervisor

from tests.testutil import new_job

KEY = "default/adopt-job"


def _wait(cond, timeout=15.0, interval=0.02):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def _pid_gone_or_zombie(pid):
    try:
        with open(f"/proc/{pid}/stat") as f:
            stat = f.read()
    except OSError:
        return True
    return stat[stat.rfind(")") + 2 :].split()[0] == "Z"


def sleeper(seconds="30"):
    return ProcessTemplate(command=["sleep", seconds])


class TestRunnerAdoption:
    def test_record_persisted_and_live_replica_adopted(self, tmp_path):
        a = SubprocessRunner(tmp_path)
        h = a.create(KEY, ReplicaType.MASTER, 0, sleeper(), {})
        name = h.name
        assert (tmp_path / "replicas").is_dir()
        rec_files = list((tmp_path / "replicas").glob("*.json"))
        assert len(rec_files) == 1
        rec = json.loads(rec_files[0].read_text())
        assert rec["name"] == name and rec["pid"] == h.pid
        assert rec.get("pid_start") is not None

        # "Crash": drop runner A without shutdown; runner B adopts.
        b = SubprocessRunner(tmp_path)
        adopted = b.get(name)
        assert adopted is not None
        assert adopted.phase == ReplicaPhase.RUNNING
        assert adopted.pid == h.pid
        assert b.list_for_job(KEY)[0].name == name

        # Adopted replicas are deletable (kill escalation works cross-parent).
        b.delete(name, grace_seconds=2.0)
        assert b.get(name) is None
        assert not list((tmp_path / "replicas").glob("*"))
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))
        a.shutdown()

    @pytest.mark.parametrize("code,phase", [(0, ReplicaPhase.SUCCEEDED), (7, ReplicaPhase.FAILED)])
    def test_exit_code_recovered_across_restart(self, tmp_path, code, phase):
        a = SubprocessRunner(tmp_path)
        t = ProcessTemplate(command=["sh", "-c", f"exit {code}"])
        h = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        # Let it finish while the supervisor is "down" (no a.sync()).
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))
        b = SubprocessRunner(tmp_path)
        got = b.get(h.name)
        assert got is not None and got.phase == phase
        assert got.exit_code == code
        assert got.finished_at is not None
        a.shutdown()

    def test_orphan_signal_death_without_exit_record_is_retryable(self, tmp_path):
        a = SubprocessRunner(tmp_path)
        h = a.create(KEY, ReplicaType.WORKER, 0, sleeper(), {})
        # SIGKILL the whole group (preemption analog): the exit-capture
        # wrapper dies too, so no exit file is written.
        os.killpg(h.pid, signal.SIGKILL)
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))
        b = SubprocessRunner(tmp_path)
        got = b.get(h.name)
        assert got.phase == ReplicaPhase.FAILED
        assert got.exit_code == 137  # retryable under ExitCode policy
        a.shutdown()

    def test_pid_reuse_guard(self, tmp_path):
        a = SubprocessRunner(tmp_path)
        h = a.create(KEY, ReplicaType.MASTER, 0, sleeper(), {})
        rec_file = next((tmp_path / "replicas").glob("*.json"))
        rec = json.loads(rec_file.read_text())
        rec["pid_start"] = rec["pid_start"] + 12345  # a different process
        rec_file.write_text(json.dumps(rec))
        b = SubprocessRunner(tmp_path)
        got = b.get(h.name)
        # Start-time mismatch ⇒ not our process ⇒ treated as dead, and the
        # live stranger must NOT be killed by delete.
        assert got.phase == ReplicaPhase.FAILED and got.exit_code == 137
        b.delete(h.name)
        assert not _pid_gone_or_zombie(h.pid)
        a.shutdown()

    def test_adopted_replica_finish_detected_by_sync(self, tmp_path):
        a = SubprocessRunner(tmp_path)
        t = ProcessTemplate(command=["sh", "-c", "sleep 0.3; exit 5"])
        h = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        b = SubprocessRunner(tmp_path)
        assert b.get(h.name).phase == ReplicaPhase.RUNNING

        def finished():
            b.sync()
            return b.get(h.name).is_finished()

        assert _wait(finished)
        got = b.get(h.name)
        assert got.phase == ReplicaPhase.FAILED and got.exit_code == 5
        a.shutdown()


def _creation_events(state_dir: Path, key: str) -> int:
    """Count SuccessfulCreateReplica OCCURRENCES in the PERSISTED event
    log — it spans supervisor incarnations (the in-memory recorder dies
    with each one). The sink may hold cumulative-count update records for
    a repeating event (the aggregation write-through), so raw lines
    over-count: merge first, then sum the merged counts."""
    from pytorch_operator_tpu.controller.events import load_merged_events

    p = state_dir / "events" / (key.replace("/", "_") + ".events.jsonl")
    return sum(
        int(rec.get("count", 1) or 1)
        for rec in load_merged_events(p)
        if rec["reason"] == "SuccessfulCreateReplica"
    )


class TestAdoptionSafety:
    def test_shutdown_spares_adopted_replicas(self, tmp_path):
        """A foreground 'tpujob run' sharing a daemon's state dir must not
        kill the daemon's world on exit: shutdown() only reaps replicas the
        same incarnation spawned (controller shutdown never kills adopted
        pods)."""
        daemon = SubprocessRunner(tmp_path)
        h = daemon.create(KEY, ReplicaType.MASTER, 0, sleeper(), {})
        fg = SubprocessRunner(tmp_path)  # adopts the daemon's replica
        assert fg.get(h.name).phase == ReplicaPhase.RUNNING
        fg.shutdown()
        assert not _pid_gone_or_zombie(h.pid)  # still running
        assert fg._record_path(h.name).exists()  # record intact
        daemon.shutdown()
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))

    @pytest.mark.parametrize("adopt", [False, True])
    def test_delete_escalates_to_kill_for_term_trapping_replica(self, tmp_path, adopt):
        """The exit-capture wrapper dies instantly on SIGTERM even when the
        replica traps it; delete() must judge termination on the whole
        process group and escalate to SIGKILL (regression: the wrapper's
        exit used to satisfy proc.wait, skipping the escalation)."""
        a = SubprocessRunner(tmp_path)
        t = ProcessTemplate(command=["sh", "-c", "trap '' TERM; sleep 30"])
        h = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        time.sleep(0.2)  # let the trap install
        runner = SubprocessRunner(tmp_path) if adopt else a
        t0 = time.time()
        runner.delete(h.name, grace_seconds=0.5)
        assert time.time() - t0 < 5.0
        # Every group member (wrapper AND the trap-sleeping replica) is gone.
        def group_empty():
            import pytorch_operator_tpu.controller.runner as r
            return not r._group_members_alive(h.pid)
        assert _wait(group_empty, timeout=5.0)
        a.shutdown()

    def test_wrapper_death_alone_does_not_kill_adoption_liveness(self, tmp_path):
        """If only the exit-capture wrapper dies (stray kill/OOM) while the
        replica's group survives, adoption must see the replica as RUNNING —
        not classify it dead and let the reconciler double-create it."""
        import pytorch_operator_tpu.controller.runner as r

        a = SubprocessRunner(tmp_path)
        t = ProcessTemplate(command=["sh", "-c", "trap '' TERM; sleep 30"])
        h = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        time.sleep(0.3)
        os.kill(h.pid, signal.SIGKILL)  # the wrapper only, not the group
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))
        assert r._group_members_alive(h.pid)  # replica survived

        b = SubprocessRunner(tmp_path)
        assert b.get(h.name).phase == ReplicaPhase.RUNNING
        b.sync()
        assert b.get(h.name).phase == ReplicaPhase.RUNNING
        b.delete(h.name, grace_seconds=0.5)
        assert _wait(lambda: not r._group_members_alive(h.pid), timeout=5.0)
        a.shutdown()

    @pytest.mark.parametrize("sync_first", [False, True])
    def test_delete_reaps_survivors_after_wrapper_predeceased(self, tmp_path, sync_first):
        """delete() must reap surviving group members even when the wrapper
        already exited — both straight from the Popen record and after a
        sync() has demoted the replica to group tracking."""
        import pytorch_operator_tpu.controller.runner as r

        a = SubprocessRunner(tmp_path)
        t = ProcessTemplate(command=["sh", "-c", "trap '' TERM; sleep 30"])
        h = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        time.sleep(0.3)
        os.kill(h.pid, signal.SIGKILL)  # wrapper only; group survives
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))
        if sync_first:
            # Signal-killed wrapper + surviving group ⇒ NOT dead: the
            # replica stays RUNNING under group tracking.
            a.sync()
            assert a.get(h.name).phase == ReplicaPhase.RUNNING
        assert r._group_members_alive(h.pid)
        a.delete(h.name, grace_seconds=0.5)
        assert _wait(lambda: not r._group_members_alive(h.pid), timeout=5.0)
        a.shutdown()

    def test_delete_many_shares_one_escalation_across_mixed_batch(self, tmp_path):
        """delete_many must tear down a batch mixing every replica kind —
        a live TERM-trapping wrapper, an adopted replica, and a dead-wrapper
        survivor group — within ONE shared grace budget (~grace+2s total,
        not per replica), and clean up every record."""
        import pytorch_operator_tpu.controller.runner as r

        a = SubprocessRunner(tmp_path)
        t = ProcessTemplate(command=["sh", "-c", "trap '' TERM; sleep 30"])
        live = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        adopted_src = a.create(KEY, ReplicaType.WORKER, 0, t, {})
        orphan = a.create(KEY, ReplicaType.WORKER, 1, t, {})
        time.sleep(0.3)  # let the traps install
        os.kill(orphan.pid, signal.SIGKILL)  # wrapper only; group survives
        assert _wait(lambda: _pid_gone_or_zombie(orphan.pid))

        b = SubprocessRunner(tmp_path)  # adopts all three
        assert b.get(adopted_src.name).phase == ReplicaPhase.RUNNING
        # Delete from the ADOPTING runner for worker-0 (adopted path) but
        # from the SPAWNING runner for the rest: a covers live-Popen and
        # dead-wrapper-survivor paths, b covers the adopted path.
        t0 = time.time()
        b.delete_many([adopted_src.name], grace_seconds=0.5)
        a.delete_many([live.name, orphan.name], grace_seconds=0.5)
        elapsed = time.time() - t0
        # Shared escalation: two batches, each ≤ grace(0.5)+2s + scan slop.
        assert elapsed < 8.0
        for h in (live, adopted_src, orphan):
            assert _wait(
                lambda h=h: not r._group_members_alive(h.pid), timeout=5.0
            )
        # The deleting runner forgets everything it tore down (the spawner
        # may keep a stale Popen record for a replica another incarnation
        # deleted — that is pre-existing adoption semantics, not a leak).
        assert live.name not in a._procs and orphan.name not in a._procs
        assert not a._adopted and adopted_src.name not in b._adopted
        assert not b._procs
        assert a.get(live.name) is None and a.get(orphan.name) is None
        assert b.get(adopted_src.name) is None
        a.shutdown()
        b.shutdown()

    def test_exit_file_wins_over_lingering_group_member(self, tmp_path):
        """A replica whose MAIN process exited (wrapper wrote the exit
        file) is done, even if a stray background child keeps the process
        group alive — adoption must not hold the job RUNNING forever."""
        a = SubprocessRunner(tmp_path)
        # Main exits 3 immediately; a detached child keeps the group alive.
        t = ProcessTemplate(command=["sh", "-c", "sleep 30 & exit 3"])
        h = a.create(KEY, ReplicaType.MASTER, 0, t, {})
        assert _wait(lambda: a._read_exit_file(h.name) is not None)
        b = SubprocessRunner(tmp_path)
        got = b.get(h.name)
        assert got.phase == ReplicaPhase.FAILED and got.exit_code == 3
        b.delete(h.name, grace_seconds=0.5)  # reaps the stray child too
        a.shutdown()

    def test_sync_does_not_resurrect_deleted_record(self, tmp_path):
        """Shared state dir: incarnation B delete()s a replica; the owning
        incarnation A's next sync() must not rewrite the record file (a
        stale FAILED record would poison the next supervisor start)."""
        a = SubprocessRunner(tmp_path)
        h = a.create(KEY, ReplicaType.MASTER, 0, sleeper(), {})
        b = SubprocessRunner(tmp_path)
        b.delete(h.name, grace_seconds=0.5)
        assert not a._record_path(h.name).exists()
        a.sync()  # A's Popen observes the death — must not re-save
        assert not a._record_path(h.name).exists()
        c = SubprocessRunner(tmp_path)
        assert c.get(h.name) is None
        a.shutdown()

    def test_corrupt_record_quarantined_not_fatal(self, tmp_path):
        a = SubprocessRunner(tmp_path)
        h = a.create(KEY, ReplicaType.MASTER, 0, sleeper(), {})
        bad = tmp_path / "replicas" / "default_broken-master-0.json"
        bad.write_text('{"name": "x", "replica_type": "NotAType"}')
        b = SubprocessRunner(tmp_path)  # must not raise
        assert b.get(h.name) is not None
        assert not bad.exists()
        assert bad.with_suffix(".json.corrupt").exists()
        a.shutdown()


class TestSupervisorRestart:
    def test_restart_adopts_world_and_does_not_double_create(self, tmp_state_dir):
        s1 = Supervisor(state_dir=tmp_state_dir, gang_enabled=True)
        job = new_job(name="adopt-e2e", workers=1)
        for rs in job.spec.replica_specs.values():
            rs.template = ProcessTemplate(command=["sh", "-c", "sleep 1.5"])
        key = s1.submit(job)
        assert _wait(
            lambda: (s1.sync_once() or True)
            and len(s1.runner.list_for_job(key)) == 2
            and all(h.phase == ReplicaPhase.RUNNING for h in s1.runner.list_for_job(key))
        )
        pids = {h.name: h.pid for h in s1.runner.list_for_job(key)}

        # Crash: NO shutdown — replicas keep running, then a fresh
        # supervisor on the same state dir takes over.
        s2 = Supervisor(state_dir=tmp_state_dir, gang_enabled=True)
        s2.sync_once()
        handles = s2.runner.list_for_job(key)
        assert {h.name: h.pid for h in handles if h.pid} == pids  # same processes
        # Only the original creations, no respawns after the restart.
        assert _creation_events(tmp_state_dir, key) == 2

        final = s2.wait(key, timeout=30)
        assert final.is_succeeded()
        s2.shutdown()
        s1.shutdown()

    def test_master_succeeded_while_supervisor_down(self, tmp_state_dir):
        s1 = Supervisor(state_dir=tmp_state_dir)
        job = new_job(name="adopt-done", workers=0)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            command=["sh", "-c", "exit 0"]
        )
        key = s1.submit(job)
        s1.sync_once()
        h = s1.runner.get(replica_name(key, ReplicaType.MASTER, 0))
        assert _wait(lambda: _pid_gone_or_zombie(h.pid))
        # Restarted supervisor must mark the job Succeeded from the
        # recovered exit record — not respawn the master.
        s2 = Supervisor(state_dir=tmp_state_dir)
        final = s2.wait(key, timeout=15)
        assert final.is_succeeded()
        assert _creation_events(tmp_state_dir, key) == 1
        s2.shutdown()
        s1.shutdown()
