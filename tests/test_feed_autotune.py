"""Pipelined device feed: autotuner control law, sharded producer pool,
rolling stall telemetry, and the close()-wakes-consumer regression.

The autotuner (data/feed_autotune.py) is pure decision logic — bounds,
grow-fast/shrink-slow hysteresis, warmup — so its law is pinned without
threads. The prefetcher tests then pin the integration: FIFO
determinism under a multi-worker pool (inline vs pipelined must train
to the identical loss), in-order error delivery, dynamic depth, the
rolling-window stall stat the heartbeat carries, and the PR-8 close
fix (a step thread blocked in ``get()`` must be woken, not parked
forever, when another thread closes the feed).
"""

from __future__ import annotations

import itertools
import threading
import time

import pytest

from pytorch_operator_tpu.data.device_prefetch import (
    STALL_WINDOW,
    DevicePrefetcher,
    PrefetchedLoader,
)
from pytorch_operator_tpu.data.feed_autotune import FeedAutotuner


# ---- control law (pure, no threads) ----


class TestFeedAutotuner:
    def test_grows_in_one_observation(self):
        at = FeedAutotuner(8, initial=2, warmup=0)
        assert at.observe(5.0) == 4  # one stall -> double
        assert at.grows == 1

    def test_grow_is_bounded_by_depth_max(self):
        at = FeedAutotuner(8, initial=2, warmup=0)
        for _ in range(10):
            at.observe(100.0)
        assert at.depth == 8  # never above the budget

    def test_never_below_floor(self):
        at = FeedAutotuner(8, initial=1, warmup=0, shrink_patience=1)
        for _ in range(50):
            at.observe(0.0)
        assert at.depth == 1  # never below 1

    def test_shrink_needs_sustained_headroom(self):
        at = FeedAutotuner(8, initial=8, warmup=0, shrink_patience=4)
        # 3 quiet observations: not enough.
        for _ in range(3):
            assert at.observe(0.0) == 8
        # A stall resets the patience counter entirely.
        assert at.observe(5.0) == 8  # already at cap: no grow, but reset
        for _ in range(3):
            assert at.observe(0.0) == 8
        # The 4th consecutive quiet observation shrinks by ONE.
        assert at.observe(0.0) == 7
        assert at.shrinks == 1

    def test_shrink_is_one_slot_at_a_time(self):
        at = FeedAutotuner(8, initial=8, warmup=0, shrink_patience=2)
        for _ in range(2):
            at.observe(0.0)
        assert at.depth == 7  # not halved — bursts need the headroom

    def test_warmup_observations_are_ignored(self):
        # The first gets ALWAYS stall (the pipe is filling): they must
        # not read as a stalling producer.
        at = FeedAutotuner(8, initial=2, warmup=3)
        for _ in range(3):
            assert at.observe(500.0) == 2
        assert at.observe(500.0) == 4  # first post-warmup stall grows

    def test_initial_clamped_to_bounds(self):
        assert FeedAutotuner(4, initial=9).depth == 4
        assert FeedAutotuner(4, initial=0).depth == 1


# ---- prefetcher integration ----


class TestDevicePrefetcher:
    def test_fifo_order_with_worker_pool(self):
        """The determinism pin's mechanism: N workers, exact production
        order out — produce() calls are serialized in ticket order, the
        reorder buffer delivers in sequence."""
        c = itertools.count()
        pf = DevicePrefetcher(
            lambda: next(c), put=lambda x: x * 10, depth=4, workers=4
        )
        try:
            assert [pf.get() for _ in range(64)] == [
                i * 10 for i in range(64)
            ]
        finally:
            pf.close()

    def test_close_wakes_blocked_consumer(self):
        """PR-8 regression (satellite): a consumer blocked in get() on
        a stalled producer must raise promptly when close() is called
        from another thread — the old queue-based get() parked it
        forever."""
        woke = threading.Event()
        outcome = {}
        pf = DevicePrefetcher(
            lambda: (time.sleep(30), 1)[1], put=lambda x: x, depth=2
        )

        def consumer():
            try:
                pf.get()
                outcome["r"] = "got a batch?!"
            except RuntimeError as e:
                outcome["r"] = str(e)
            woke.set()

        t = threading.Thread(target=consumer, daemon=True)
        t.start()
        time.sleep(0.2)  # let it block inside get()
        closer = threading.Thread(target=pf.close, daemon=True)
        closer.start()
        assert woke.wait(2.0), "consumer still blocked after close()"
        assert outcome["r"] == "prefetcher is closed"

    def test_get_after_close_raises(self):
        pf = DevicePrefetcher(lambda: 1, put=lambda x: x, depth=2)
        pf.get()
        pf.close()
        with pytest.raises(RuntimeError, match="closed"):
            pf.get()

    def test_error_delivered_in_order_with_workers(self):
        """A produce() failure surfaces at ITS position: every batch
        produced before it drains first, then the error raises (and
        keeps raising)."""
        c = itertools.count()

        def boom():
            v = next(c)
            if v == 5:
                raise ValueError("decode exploded")
            return v

        pf = DevicePrefetcher(boom, put=lambda x: x, depth=3, workers=3)
        try:
            got = [pf.get() for _ in range(5)]
            assert got == [0, 1, 2, 3, 4]
            with pytest.raises(ValueError, match="decode exploded"):
                pf.get()
            with pytest.raises(ValueError, match="decode exploded"):
                pf.get()  # still failed; never skips past the error
        finally:
            pf.close()

    def test_set_depth_clamps_to_bounds(self):
        pf = DevicePrefetcher(lambda: 1, put=lambda x: x, depth=2, depth_max=6)
        try:
            pf.set_depth(100)
            assert pf.depth == 6
            pf.set_depth(0)
            assert pf.depth == 1
        finally:
            pf.close()

    def test_autotune_grows_depth_on_stall_within_max(self):
        gate = threading.Event()

        def stalling_produce():
            gate.wait(0.05)  # every batch is slow: the consumer stalls
            return 1

        pf = DevicePrefetcher(
            stalling_produce, put=lambda x: x, depth=1, depth_max=4,
            autotune=True,
        )
        try:
            for _ in range(STALL_WINDOW):
                pf.get()
            assert 1 < pf.depth <= 4
            assert pf.stats()["depth"] == pf.depth
        finally:
            pf.close()

    def test_rolling_stall_stat_reflects_recent_burst(self):
        """Satellite: the lifetime average dilutes a recent burst; the
        rolling window must not. A long healthy phase then a stall
        burst -> recent >> lifetime avg."""
        slow = threading.Event()
        produced = itertools.count()

        def produce():
            n = next(produced)
            if slow.is_set():
                time.sleep(0.02)
            return n

        pf = DevicePrefetcher(produce, put=lambda x: x, depth=1)
        try:
            for _ in range(400):  # healthy phase, near-zero waits
                pf.get()
            slow.set()
            for _ in range(STALL_WINDOW):  # burst phase fills the window
                pf.get()
            s = pf.stats()
            assert s["feed_stall_ms_recent"] > 5.0, s
            # Lifetime mean is diluted by the 400 healthy gets...
            assert s["feed_stall_ms_avg"] < s["feed_stall_ms_recent"], s
            # ...and both fields coexist (back-compat contract).
            assert "feed_stall_ms_avg" in s and "gets" in s
        finally:
            pf.close()

    def test_heartbeat_carries_recent_not_lifetime(self):
        from pytorch_operator_tpu.workloads.trainer import heartbeat_reporter

        class FakeFeed:
            def stats(self):
                return {
                    "feed_stall_ms_avg": 0.01,
                    "feed_stall_ms_recent": 42.0,
                }

        records = []
        report = heartbeat_reporter(
            lambda step, **kw: records.append(kw), feed=FakeFeed()
        )
        report(1, 0.5, 10.0)
        assert records[0]["feed_stall_ms"] == 42.0

    def test_prefetched_loader_passes_pool_knobs(self):
        class FakeLoader:
            batches_per_epoch = 4

            def __init__(self):
                self._n = itertools.count()

            def next_batch(self):
                n = next(self._n)
                import numpy as np

                return 0, n, {"x": np.full((2,), n, np.float32)}

            def close(self):
                pass

        pl = PrefetchedLoader(
            FakeLoader(), 2, put=lambda f: f, workers=3, depth_max=4,
            autotune=True,
        )
        try:
            idx = [pl.next_batch()[1] for _ in range(12)]
            assert idx == list(range(12))  # FIFO across the pool
        finally:
            pl.close()


# ---- determinism pin: inline vs pipelined identical training ----


@pytest.mark.bench_smoke
def test_inline_vs_pipelined_feed_same_final_loss():
    """THE data-plane determinism contract: moving the feed onto a
    multi-worker autotuned pool changes WHERE batches are produced,
    never WHICH batches arrive in what order — the final loss is
    bit-identical to the inline loop."""
    import tests.jaxenv  # noqa: F401

    import jax

    from pytorch_operator_tpu.workloads.dataplane_bench import _build_model

    def train(feed_mode: str) -> float:
        init_state, train_step, host_batch = _build_model(32, 16)
        state = init_state()
        if feed_mode == "inline":
            feeds = (
                jax.device_put(host_batch(i)) for i in range(20)
            )
            get = lambda: next(feeds)  # noqa: E731
            close = lambda: None  # noqa: E731
        else:
            c = itertools.count()
            pf = DevicePrefetcher(
                lambda: host_batch(next(c)),
                put=jax.device_put,
                depth=2,
                depth_max=6,
                workers=4,
                autotune=True,
            )
            get, close = pf.get, pf.close
        try:
            for _ in range(20):
                state, loss = train_step(state, get())
            return float(jax.device_get(loss))
        finally:
            close()

    assert train("inline") == train("pipelined")
