"""Checkpoint corruption detection and last-verified-good fallback
(checkpoint/integrity.py + manager.py restore hardening).

A preempted host or torn write corrupts exactly the newest checkpoint —
the one restart-based recovery reaches for first. These tests damage a
saved step (bit-flip under a stale checksum sidecar, and truncation
that makes orbax itself choke) and pin the contract: restore SKIPS the
bad step, falls back to the previous good one, and emits a
``checkpoint_corrupt`` event on the status channel the supervisor folds
into ``tpujob describe``.
"""

import json

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401  (forces CPU backend with 8 devices)

from pytorch_operator_tpu.checkpoint import CheckpointManager, integrity

pytestmark = pytest.mark.chaos


@pytest.fixture
def ckpt_dir(tmp_path):
    return tmp_path / "ckpts"


def _state(step_val: float):
    import jax.numpy as jnp

    return {
        "params": {"w": jnp.full((8, 4), step_val), "b": jnp.zeros((4,))},
        "step": jnp.asarray(int(step_val)),
    }


def _save_steps(ckpt_dir, steps):
    with CheckpointManager(ckpt_dir, max_to_keep=10) as mgr:
        for s in steps:
            mgr.save(s, _state(float(s)))


def test_sidecars_written_and_verified(ckpt_dir):
    _save_steps(ckpt_dir, [1, 2])
    assert integrity.verify_step(ckpt_dir, 1) is True
    assert integrity.verify_step(ckpt_dir, 2) is True
    with CheckpointManager(ckpt_dir) as mgr:
        assert mgr.latest_verified_step() == 2


def test_bitflip_detected_restore_falls_back(ckpt_dir, monkeypatch, tmp_path):
    _save_steps(ckpt_dir, [1, 2, 3])
    integrity.corrupt_step(ckpt_dir, 3, mode="flip")
    assert integrity.verify_step(ckpt_dir, 3) is False
    # The corruption event lands on the status channel.
    status = tmp_path / "status"
    status.mkdir()
    monkeypatch.setenv("TPUJOB_STATUS_DIR", str(status))
    monkeypatch.setenv("TPUJOB_REPLICA_TYPE", "Master")
    monkeypatch.setenv("TPUJOB_REPLICA_INDEX", "0")
    with CheckpointManager(ckpt_dir, max_to_keep=10) as mgr:
        step, state = mgr.restore_or_none(_state(0.0))
    assert step == 2
    np.testing.assert_allclose(np.asarray(state["params"]["w"]), 2.0)
    recs = [
        json.loads(line)
        for line in (status / "master-0.jsonl").read_text().splitlines()
    ]
    corrupt = [r for r in recs if r["event"] == "checkpoint_corrupt"]
    assert corrupt and corrupt[0]["step"] == 3
    assert corrupt[0]["fallback"] == 2


def test_truncation_that_orbax_rejects_falls_back(ckpt_dir):
    """Even without a checksum mismatch (sidecar removed -> 'unknown'),
    a restore failure on the damaged step must degrade to the previous
    step, not kill the recovery."""
    _save_steps(ckpt_dir, [1, 2])
    integrity.corrupt_step(ckpt_dir, 2, mode="truncate")
    integrity.sidecar_path(ckpt_dir, 2).unlink()  # no digest to flag it
    with CheckpointManager(ckpt_dir, max_to_keep=10) as mgr:
        step, state = mgr.restore_or_none(_state(0.0))
    assert step == 1
    np.testing.assert_allclose(np.asarray(state["params"]["w"]), 1.0)


def test_all_steps_corrupt_returns_none(ckpt_dir):
    _save_steps(ckpt_dir, [1])
    integrity.corrupt_step(ckpt_dir, 1, mode="flip")
    with CheckpointManager(ckpt_dir, max_to_keep=10) as mgr:
        assert mgr.restore_or_none(_state(0.0)) is None
        # Opting out of verification restores the newest step blindly
        # (legacy behavior stays reachable).
        assert mgr.latest_step() == 1


def test_transient_write_failure_retried(ckpt_dir, monkeypatch):
    """A fail_checkpoint_write fault makes the first save attempt raise;
    the shared backoff retry must land the checkpoint anyway."""
    from pytorch_operator_tpu import faults
    from pytorch_operator_tpu.faults import Fault, FaultPlan

    faults.disarm()
    faults.arm(
        FaultPlan(faults=[Fault(kind="fail_checkpoint_write", nth=1)])
    )
    try:
        with CheckpointManager(ckpt_dir) as mgr:
            mgr.save(1, _state(1.0))
            assert mgr.latest_verified_step() == 1
    finally:
        faults.disarm()


def test_stale_sidecars_pruned_with_retention(ckpt_dir):
    _save_steps(ckpt_dir, [1, 2])
    with CheckpointManager(ckpt_dir, max_to_keep=2) as mgr:
        for s in (3, 4):
            mgr.save(s, _state(float(s)))
        kept = set(mgr.all_steps())
    digests = {
        int(p.name[: -len(".digest")])
        for p in ckpt_dir.glob("*.digest")
    }
    assert digests == kept
