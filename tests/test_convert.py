"""PyTorchJob → TPUJob conversion (the migration shim, api/convert.py).

A user of the reference submits ``kind: PyTorchJob`` manifests
(kubeflow.org/v1, camelCase, pod templates); these must load, default,
validate, and run through the supervisor unchanged.
"""

from __future__ import annotations

import pytest

from pytorch_operator_tpu.api import (
    CleanPodPolicy,
    ReplicaType,
    RestartPolicy,
    ValidationError,
    loads_job,
    set_defaults,
    validate,
)
from pytorch_operator_tpu.api.convert import CONVERTED_FROM_ANNOTATION

MNIST_PYTORCHJOB = """
apiVersion: kubeflow.org/v1
kind: PyTorchJob
metadata:
  name: mnist
  namespace: team-a
  labels: {app: mnist}
spec:
  runPolicy:
    cleanPodPolicy: All
    ttlSecondsAfterFinished: 120
    backoffLimit: 3
    schedulingPolicy:
      minAvailable: 2
      queue: training
      priorityClass: high
  pytorchReplicaSpecs:
    Master:
      replicas: 1
      restartPolicy: OnFailure
      template:
        spec:
          containers:
            - name: pytorch
              image: gcr.io/kubeflow/mnist:latest
              command: [python, /opt/mnist.py]
              args: ["--epochs", "2"]
              env:
                - name: LR
                  value: "0.01"
                - name: SECRET
                  valueFrom: {secretKeyRef: {name: s, key: k}}
              ports:
                - name: pytorchjob-port
                  containerPort: 23456
              resources:
                limits: {google.com/tpu: 4}
    Worker:
      replicas: 2
      restartPolicy: ExitCode
      template:
        spec:
          containers:
            - name: pytorch
              command: [python, /opt/mnist.py]
"""


class TestConvert:
    def test_full_manifest_maps(self):
        job = loads_job(MNIST_PYTORCHJOB)
        assert job.kind == "TPUJob"
        assert job.metadata.name == "mnist"
        assert job.metadata.namespace == "team-a"
        assert job.metadata.labels == {"app": "mnist"}
        assert "kubeflow.org/v1 PyTorchJob" in job.metadata.annotations[
            CONVERTED_FROM_ANNOTATION
        ]

        master = job.spec.replica_specs[ReplicaType.MASTER]
        assert master.replicas == 1
        assert master.restart_policy == RestartPolicy.ON_FAILURE
        assert master.template.command == ["python", "/opt/mnist.py"]
        assert master.template.args == ["--epochs", "2"]
        assert master.template.env == {"LR": "0.01"}
        assert master.template.resources.tpu_chips == 4

        worker = job.spec.replica_specs[ReplicaType.WORKER]
        assert worker.replicas == 2
        assert worker.restart_policy == RestartPolicy.EXIT_CODE

        rp = job.spec.run_policy
        assert rp.clean_pod_policy == CleanPodPolicy.ALL
        assert rp.ttl_seconds_after_finished == 120
        assert rp.backoff_limit == 3
        assert rp.scheduling_policy.min_available == 2
        assert rp.scheduling_policy.queue == "training"
        assert job.spec.port == 23456

        # What cannot map is surfaced as annotations, not dropped silently.
        ann = job.metadata.annotations
        assert ann["tpujob.dev/converted-image-master"].startswith("gcr.io/")
        assert ann["tpujob.dev/converted-env-dropped-master"] == "SECRET"
        assert ann["tpujob.dev/converted-priority-class"] == "high"

        # The converted job passes the normal defaulting + validation path.
        set_defaults(job)
        validate(job)

    def test_v1beta2_spec_level_run_policy(self):
        job = loads_job(
            """
apiVersion: kubeflow.org/v1beta2
kind: PyTorchJob
metadata: {name: old}
spec:
  cleanPodPolicy: None
  ttlSecondsAfterFinished: 60
  pytorchReplicaSpecs:
    Master:
      replicas: 1
      template:
        spec:
          containers:
            - {name: pytorch, command: [sh, -c, "exit 0"]}
"""
        )
        assert job.spec.run_policy.clean_pod_policy == CleanPodPolicy.NONE
        assert job.spec.run_policy.ttl_seconds_after_finished == 60

    def test_elastic_policy_maps(self):
        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: el}
spec:
  elasticPolicy: {minReplicas: 1, maxReplicas: 4, maxRestarts: 7, nProcPerNode: 2}
  pytorchReplicaSpecs:
    Master:
      template:
        spec:
          containers: [{name: pytorch, command: [sh, -c, "exit 0"]}]
    Worker:
      replicas: 2
      template:
        spec:
          containers: [{name: pytorch, command: [sh, -c, "exit 0"]}]
"""
        )
        ep = job.spec.elastic_policy
        assert (ep.min_replicas, ep.max_replicas, ep.max_restarts) == (1, 4, 7)
        assert job.metadata.annotations["tpujob.dev/converted-nproc-per-node"] == "2"

    def test_image_without_command_is_a_clear_error(self):
        with pytest.raises(ValueError, match="no command"):
            loads_job(
                """
kind: PyTorchJob
metadata: {name: img}
spec:
  pytorchReplicaSpecs:
    Master:
      template:
        spec:
          containers: [{name: pytorch, image: gcr.io/x/entrypoint-only}]
"""
            )

    def test_converted_job_runs_end_to_end(self, tmp_path):
        """A PyTorchJob manifest drives the real supervisor to completion."""
        from pytorch_operator_tpu.controller.supervisor import Supervisor

        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: converted-e2e}
spec:
  pytorchReplicaSpecs:
    Master:
      restartPolicy: OnFailure
      template:
        spec:
          containers:
            - name: pytorch
              command: [sh, -c, "echo converted; exit 0"]
"""
        )
        sup = Supervisor(state_dir=tmp_path / "state")
        final = sup.run(job, timeout=30)
        assert final.is_succeeded()
        sup.shutdown()

    def test_example_manifest_loads(self):
        from pytorch_operator_tpu.api import load_job

        job = load_job("examples/pytorchjob-migration.yaml")
        set_defaults(job)
        validate(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 1

    def test_all_dropped_env_vars_surfaced(self):
        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: secrets}
spec:
  pytorchReplicaSpecs:
    Master:
      template:
        spec:
          containers:
            - name: pytorch
              command: [sh, -c, "exit 0"]
              env:
                - {name: DB_PASS, valueFrom: {secretKeyRef: {name: s, key: a}}}
                - {name: API_KEY, valueFrom: {secretKeyRef: {name: s, key: b}}}
"""
        )
        assert (
            job.metadata.annotations["tpujob.dev/converted-env-dropped-master"]
            == "DB_PASS,API_KEY"
        )

    def test_dropped_pod_fields_and_resources_surfaced(self):
        """nodeSelector/tolerations/volumes/initContainers/affinity and
        non-TPU resource limits must land in converted-* annotations, not
        vanish (the module docstring's 'surfaced, not silently dropped')."""
        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: podfields}
spec:
  pytorchReplicaSpecs:
    Master:
      template:
        spec:
          nodeSelector: {cloud.google.com/gke-tpu-topology: 2x2}
          tolerations: [{key: tpu, operator: Exists}]
          volumes: [{name: data, emptyDir: {}}]
          affinity: {nodeAffinity: {}}
          initContainers:
            - name: wait-for-master
              command: [sh, -c, "until nslookup $MASTER_ADDR; do sleep 1; done"]
          containers:
            - name: pytorch
              command: [sh, -c, "exit 0"]
              resources:
                limits: {google.com/tpu: 4, cpu: "8", memory: 16Gi}
"""
        )
        ann = job.metadata.annotations
        dropped = ann["tpujob.dev/converted-dropped-master"].split(",")
        for k in (
            "nodeSelector",
            "tolerations",
            "volumes",
            "affinity",
            "initContainers",
        ):
            assert k in dropped
        assert (
            ann["tpujob.dev/converted-init-containers-master"]
            == "wait-for-master"
        )
        assert (
            ann["tpujob.dev/converted-resources-dropped-master"]
            == "cpu,memory"
        )

    def test_sidecar_commands_surfaced(self):
        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: sidecars}
spec:
  pytorchReplicaSpecs:
    Master:
      template:
        spec:
          containers:
            - name: pytorch
              command: [sh, -c, "exit 0"]
            - name: tensorboard
              command: [tensorboard, --logdir, /logs]
            - name: proxy
"""
        )
        assert (
            job.metadata.annotations["tpujob.dev/converted-sidecars-master"]
            == "tensorboard=tensorboard --logdir /logs;proxy=<image entrypoint>"
        )

    def test_master_port_wins_over_worker(self):
        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: ports}
spec:
  pytorchReplicaSpecs:
    Worker:
      replicas: 1
      template:
        spec:
          containers:
            - name: pytorch
              command: [sh, -c, "exit 0"]
              ports: [{name: pytorchjob-port, containerPort: 29500}]
    Master:
      template:
        spec:
          containers:
            - name: pytorch
              command: [sh, -c, "exit 0"]
              ports: [{name: pytorchjob-port, containerPort: 23456}]
"""
        )
        assert job.spec.port == 23456

    def test_missing_replica_specs_rejected(self):
        with pytest.raises(ValueError, match="pytorchReplicaSpecs"):
            loads_job("kind: PyTorchJob\nmetadata: {name: x}\nspec: {}")

    def test_native_tpujob_yaml_unaffected(self):
        job = loads_job(
            """
api_version: tpujob.dev/v1
kind: TPUJob
metadata: {name: plain}
spec:
  replica_specs:
    Master: {replicas: 1, template: {module: pytorch_operator_tpu.workloads.noop}}
"""
        )
        assert CONVERTED_FROM_ANNOTATION not in job.metadata.annotations
