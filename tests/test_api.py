"""Tests for the TPUJob API layer: types, defaulting, validation, YAML.

Modeled on the reference's api unit tests (``pkg/apis/pytorch/v1/*_test.go``,
SURVEY.md §4): build fixtures, default them, assert invariants.
"""

import pytest

from pytorch_operator_tpu.api import (
    DEFAULT_PORT,
    CleanPodPolicy,
    ConditionType,
    ElasticPolicy,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    TPUJob,
    ValidationError,
    dump_job,
    loads_job,
    set_defaults,
    validate,
    validate_spec,
)
from tests.testutil import new_job


class TestDefaults:
    def test_port_default(self):
        job = new_job(defaulted=False)
        assert job.spec.port is None
        set_defaults(job)
        assert job.spec.port == DEFAULT_PORT

    def test_replicas_default_to_one(self):
        job = new_job(defaulted=False)
        job.spec.replica_specs[ReplicaType.WORKER].replicas = None
        set_defaults(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 1

    def test_restart_policy_default(self):
        job = new_job(defaulted=False)
        job.spec.replica_specs[ReplicaType.MASTER].restart_policy = None
        set_defaults(job)
        assert (
            job.spec.replica_specs[ReplicaType.MASTER].restart_policy
            == RestartPolicy.ON_FAILURE
        )

    def test_clean_pod_policy_default(self):
        job = new_job(defaulted=False)
        set_defaults(job)
        assert job.spec.run_policy.clean_pod_policy == CleanPodPolicy.RUNNING

    def test_gang_min_available_defaults_to_total(self):
        job = new_job(workers=3, defaulted=False)
        set_defaults(job)
        assert job.spec.run_policy.scheduling_policy.min_available == 4

    def test_idempotent(self):
        job = new_job(workers=2)
        before = job.to_dict()
        set_defaults(job)
        assert job.to_dict() == before


class TestValidation:
    def test_valid_job_passes(self):
        validate(new_job(workers=2))

    def test_missing_master_rejected(self):
        job = new_job(workers=2)
        del job.spec.replica_specs[ReplicaType.MASTER]
        with pytest.raises(ValidationError, match="Master"):
            validate(job)

    def test_master_replicas_must_be_one(self):
        job = new_job()
        job.spec.replica_specs[ReplicaType.MASTER].replicas = 2
        with pytest.raises(ValidationError, match="must be 1"):
            validate(job)

    def test_template_requires_runnable(self):
        job = new_job()
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate()
        with pytest.raises(ValidationError, match="command.*module|module.*command"):
            validate(job)

    def test_command_and_module_exclusive(self):
        job = new_job()
        t = job.spec.replica_specs[ReplicaType.MASTER].template
        t.command = ["python", "x.py"]
        with pytest.raises(ValidationError, match="mutually exclusive"):
            validate(job)

    def test_bad_name_rejected(self):
        job = new_job(name="Bad_Name!")
        with pytest.raises(ValidationError, match="DNS-1123"):
            validate(job)

    def test_empty_name_rejected(self):
        job = new_job(name="")
        with pytest.raises(ValidationError, match="empty"):
            validate(job)

    def test_bad_port(self):
        job = new_job()
        job.spec.port = 70000
        errs = validate_spec(job.spec)
        assert any("port" in e for e in errs)

    def test_negative_backoff_limit(self):
        job = new_job(backoff_limit=-1)
        with pytest.raises(ValidationError, match="backoff_limit"):
            validate(job)

    def test_elastic_bounds(self):
        job = new_job(workers=5, elastic=ElasticPolicy(min_replicas=2, max_replicas=4))
        with pytest.raises(ValidationError, match="within"):
            validate(job)
        job2 = new_job(workers=3, elastic=ElasticPolicy(min_replicas=2, max_replicas=4))
        validate(job2)

    def test_elastic_min_leq_max(self):
        job = new_job(workers=3, elastic=ElasticPolicy(min_replicas=4, max_replicas=2))
        with pytest.raises(ValidationError, match="max_replicas"):
            validate(job)

    def test_min_available_cannot_exceed_total(self):
        job = new_job(workers=1)
        job.spec.run_policy.scheduling_policy.min_available = 10
        with pytest.raises(ValidationError, match="min_available"):
            validate(job)


# Condition state-machine semantics (exclusivity matrix, timestamps) live
# in tests/test_conditions.py — the single home for that coverage.


class TestSerialization:
    def test_round_trip_dict(self):
        job = new_job(
            workers=3,
            backoff_limit=5,
            ttl_seconds_after_finished=60,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=3),
        )
        job.set_condition(ConditionType.CREATED)
        job2 = TPUJob.from_dict(job.to_dict())
        assert job2.to_dict() == job.to_dict()

    def test_round_trip_yaml(self):
        job = new_job(workers=2)
        text = dump_job(job)
        job2 = loads_job(text)
        assert job2.to_dict() == job.to_dict()

    def test_load_user_yaml(self):
        text = """
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: mnist
spec:
  replica_specs:
    Master:
      replicas: 1
      template:
        module: pytorch_operator_tpu.workloads.mnist_train
        args: ["--epochs", "1"]
    Worker:
      replicas: 2
      restart_policy: ExitCode
      template:
        module: pytorch_operator_tpu.workloads.mnist_train
  run_policy:
    backoff_limit: 3
"""
        job = loads_job(text)
        set_defaults(job)
        validate(job)
        assert job.spec.replica_specs[ReplicaType.WORKER].replicas == 2
        assert (
            job.spec.replica_specs[ReplicaType.WORKER].restart_policy
            == RestartPolicy.EXIT_CODE
        )
        assert job.spec.total_replicas() == 3
        assert job.spec.port == DEFAULT_PORT

    def test_replica_spec_round_trip(self):
        rs = ReplicaSpec(
            replicas=2,
            restart_policy=RestartPolicy.EXIT_CODE,
            template=ProcessTemplate(command=["echo", "hi"], env={"A": "1"}),
        )
        rs2 = ReplicaSpec.from_dict(rs.to_dict())
        assert rs2.to_dict() == rs.to_dict()


class TestEnumParseErrors:
    def test_unknown_restart_policy_has_field_path(self):
        text = """
metadata: {name: x}
spec:
  replica_specs:
    Master: {restart_policy: Sometimes, template: {module: m}}
"""
        with pytest.raises(ValueError, match=r"replica_specs\[Master\].restart_policy.*valid:"):
            loads_job(text)

    def test_unknown_replica_type_key(self):
        with pytest.raises(ValueError, match="replica_specs key.*valid: Master, Worker"):
            loads_job("metadata: {name: x}\nspec:\n  replica_specs:\n    Chief: {template: {module: m}}")

    def test_non_integer_replicas(self):
        with pytest.raises(ValueError, match=r"replica_specs\[Master\].replicas: invalid integer 'two'"):
            loads_job("metadata: {name: x}\nspec:\n  replica_specs:\n    Master: {replicas: two, template: {module: m}}")

    def test_min_available_checked_undefaulted(self):
        job = new_job(workers=1, defaulted=False)
        job.spec.run_policy.scheduling_policy.min_available = 10
        errs = validate_spec(job.spec)
        assert any("min_available" in e for e in errs)


class TestNamespaceValidation:
    def test_underscore_namespace_rejected(self):
        job = new_job(name="ok")
        job.metadata.namespace = "team_a"
        with pytest.raises(ValidationError, match="metadata.namespace"):
            validate(job)


class TestTemplateParsing:
    def test_scalar_command_rejected(self):
        with pytest.raises(ValueError, match="list of argv strings"):
            loads_job(
                "metadata: {name: x}\nspec:\n  replica_specs:\n"
                "    Master: {template: {command: 'python train.py'}}"
            )

    def test_bool_env_coerced_yaml_style(self):
        job = loads_job(
            "metadata: {name: x}\nspec:\n  replica_specs:\n"
            "    Master: {template: {module: m, env: {DEBUG: true, N: 3}}}"
        )
        t = job.spec.replica_specs[ReplicaType.MASTER].template
        assert t.env == {"DEBUG": "true", "N": "3"}

    def test_structured_env_rejected(self):
        with pytest.raises(ValueError, match="env values must be scalar"):
            loads_job(
                "metadata: {name: x}\nspec:\n  replica_specs:\n"
                "    Master: {template: {module: m, env: {A: [1, 2]}}}"
            )

    def test_bad_port_string(self):
        with pytest.raises(ValueError, match="spec.port: invalid integer"):
            loads_job(
                "metadata: {name: x}\nspec:\n  port: eighty\n  replica_specs:\n"
                "    Master: {template: {module: m}}"
            )
