"""ResNet + bench + driver-entry tests on the virtual CPU mesh."""

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401


class TestResNetModel:
    def test_forward_shapes_and_dtype(self):
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.models.resnet import ResNet

        model = ResNet(stage_sizes=[1, 1], num_filters=8, num_classes=10)
        variables = model.init(
            jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False
        )
        logits = model.apply(
            variables, jnp.zeros((4, 32, 32, 3)), train=False
        )
        assert logits.shape == (4, 10)
        assert logits.dtype == jnp.float32  # head stays f32 for stable loss
        assert "batch_stats" in variables

    def test_bf16_bn_stats_mode_trains_finite(self):
        """The experimental bn_f32_stats=False path (bf16 BN reductions,
        BASELINE.md A/B note) must produce finite logits and stats."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from pytorch_operator_tpu.models.resnet import ResNet

        model = ResNet(
            stage_sizes=[1, 1], num_filters=8, num_classes=10, bn_f32_stats=False
        )
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((4, 32, 32, 3)),
            jnp.float32,
        )
        variables = model.init(jax.random.key(0), x, train=False)
        logits, updates = model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        assert bool(jnp.isfinite(logits).all())
        mean_leaf = jax.tree.leaves(updates["batch_stats"])[0]
        assert mean_leaf.dtype == jnp.bfloat16  # stats really are bf16
        assert all(
            bool(jnp.isfinite(leaf.astype(jnp.float32)).all())
            for leaf in jax.tree.leaves(updates["batch_stats"])
        )

    def test_train_step_updates_params_and_stats(self):
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_operator_tpu.models.resnet import ResNet
        from pytorch_operator_tpu.parallel import make_mesh
        from pytorch_operator_tpu.workloads.resnet_bench import (
            build_train_state,
            make_train_step,
        )

        model = ResNet(stage_sizes=[1], num_filters=8, num_classes=10, dtype=jnp.float32)
        mesh = make_mesh("dp=8")
        params, stats, opt_state, tx = build_train_state(
            model, mesh, lr=0.1, momentum=0.9, seed=0, image_size=16
        )
        step = make_train_step(model, tx)
        bx = jnp.ones((8, 16, 16, 3))
        by = jnp.zeros((8,), jnp.int32)
        p2, s2, o2, loss = step(params, stats, opt_state, bx, by)
        assert np.isfinite(float(loss))
        # params moved
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), params, p2)
        assert max(jax.tree.leaves(diffs)) > 0
        # BN stats moved
        sdiffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), stats, s2)
        assert max(jax.tree.leaves(sdiffs)) > 0


class TestBench:
    @pytest.mark.slow
    def test_bench_smoke_emits_schema(self, capsys):
        import bench

        result = bench.run(["--smoke", "--steps", "2", "--warmup", "1"])
        # Round-4 shape: the artifact LEADS with the flagship LM (the
        # MFU carrier); ResNet rides as the continuity sub-block.
        assert set(result) == {
            "metric",
            "value",
            "unit",
            "mfu",
            "config",
            "seq_len",
            "final_loss",
            "resnet",
            "schedule_to_first_step_s",
        }
        assert result["value"] > 0
        assert result["unit"] == "tokens/sec/chip"
        assert set(result["mfu"]) == {
            "model_tflops_per_sec",
            "vs_peak_pct",
            "vs_sustained_matmul_pct",
        }
        rn = result["resnet"]
        assert rn["unit"] == "images/sec/chip" and rn["value"] > 0
        assert rn["vs_baseline"] > 0
        # The latency probe runs REAL supervisor jobs even in smoke mode
        # (with a pre-warmed standby, the production daemon config);
        # both phases must come back measured, not None.
        lat = result["schedule_to_first_step_s"]
        assert lat["cold"] > 0 and lat["warm"] > 0

    @pytest.mark.slow
    def test_bench_smoke_no_latency_flag(self):
        import bench

        result = bench.run(
            ["--smoke", "--steps", "2", "--warmup", "1", "--no-latency"]
        )
        assert set(result) == {
            "metric", "value", "unit", "mfu", "config", "seq_len",
            "final_loss", "resnet",
        }

    def test_mfu_math(self):
        import bench

        # 164 TF/s of model FLOPs == 100% of sustained, ~83% of peak.
        m = bench.mfu(164e12)
        assert m["vs_sustained_matmul_pct"] == 100.0
        assert 80 < m["vs_peak_pct"] < 85
        # The LM formula: 6N dominates at short S.
        f = bench.lm_train_flops_per_token(1e9, 16, 1024, 64)
        assert abs(f - (6e9 + 6 * 16 * 64 * 1024)) < 1


class TestDataFileMode:
    @pytest.mark.slow
    def test_trains_from_packed_file(self, tmp_path):
        """Real-data path: distinct per-step batches from the native
        prefetch loader, scanned inside one dispatch."""
        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        out = tmp_path / "syn.bin"
        assert pack_main([
            "--out", str(out), "--dataset", "synthetic",
            "--n", "64", "--height", "32", "--width", "32", "--classes", "10",
        ]) == 0
        result = run_benchmark(
            depth=18,
            batch_size=16,
            classes=10,
            steps=4,
            warmup=2,
            data_file=str(out),
            log=lambda *_: None,
        )
        assert result["input"] == "file"
        assert np.isfinite(result["final_loss"])
        assert result["value"] > 0

    @pytest.mark.slow
    def test_labels_exceeding_classes_rejected(self, tmp_path):
        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        out = tmp_path / "syn.bin"
        pack_main([
            "--out", str(out), "--dataset", "synthetic",
            "--n", "32", "--height", "16", "--width", "16", "--classes", "10",
        ])
        with pytest.raises(ValueError, match="classes"):
            run_benchmark(
                depth=18, batch_size=16, classes=4, steps=2, warmup=1,
                data_file=str(out), log=lambda *_: None,
            )

    @pytest.mark.slow
    def test_bad_label_beyond_first_chunk_rejected(self, tmp_path):
        """ADVICE r2: the old first-chunk latch sampled only the first
        drawn batches; a bad label in a later record one-hotted to a zero
        row and silently deflated the loss. The whole-file field_range
        scan must reject it up front — before any batch is drawn."""
        import numpy as np

        from pytorch_operator_tpu.data import pack_arrays
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        n = 64
        x = np.random.default_rng(0).random((n, 16, 16, 3), np.float32)
        y = np.full((n,), 3, np.int32)
        y[-1] = 10  # out of range, and outside any first-chunk sample
        out = tmp_path / "bad-tail.bin"
        pack_arrays(out, {"x": x, "y": y})
        with pytest.raises(ValueError, match="classes"):
            run_benchmark(
                depth=18, batch_size=16, classes=10, steps=2, warmup=1,
                data_file=str(out), log=lambda *_: None,
            )
        y[-1] = -1  # negative ids are just as silent in one_hot
        out2 = tmp_path / "bad-neg.bin"
        pack_arrays(out2, {"x": x, "y": y})
        with pytest.raises(ValueError, match="classes"):
            run_benchmark(
                depth=18, batch_size=16, classes=10, steps=2, warmup=1,
                data_file=str(out2), log=lambda *_: None,
            )

    def test_file_smaller_than_batch_rejected(self, tmp_path):
        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        out = tmp_path / "tiny.bin"
        pack_main([
            "--out", str(out), "--dataset", "synthetic",
            "--n", "8", "--height", "16", "--width", "16",
        ])
        with pytest.raises(ValueError, match="records < global batch"):
            run_benchmark(
                depth=18, batch_size=64, steps=2, warmup=1,
                data_file=str(out), log=lambda *_: None,
            )


class TestProfileTrace:
    @pytest.mark.slow
    def test_profile_dir_writes_trace(self, tmp_path):
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        prof = tmp_path / "trace"
        run_benchmark(
            depth=18,
            batch_size=8,
            image_size=32,
            classes=10,
            steps=2,
            warmup=1,
            profile_dir=str(prof),
            log=lambda *_: None,
        )
        # jax.profiler writes <dir>/plugins/profile/<ts>/*.xplane.pb
        assert list(prof.rglob("*.xplane.pb")), "no profiler trace written"


class TestTimeline:
    def test_job_timeline_spans(self):
        from pytorch_operator_tpu.api.types import TPUJob
        from pytorch_operator_tpu.controller.supervisor import job_timeline

        job = TPUJob.from_dict({"metadata": {"name": "t"}})
        job.status.submit_time = 100.0
        job.status.start_time = 101.0
        job.status.first_step_time = 105.0
        job.status.completion_time = 110.0
        spans = dict(job_timeline(job))
        assert spans["submit -> replicas launched"] == pytest.approx(1.0)
        assert spans["launch -> first step"] == pytest.approx(4.0)
        assert spans["first step -> finished"] == pytest.approx(5.0)
        assert spans["total (submit -> finished)"] == pytest.approx(10.0)

    def test_job_timeline_partial(self):
        from pytorch_operator_tpu.api.types import TPUJob
        from pytorch_operator_tpu.controller.supervisor import job_timeline

        job = TPUJob.from_dict({"metadata": {"name": "t"}})
        assert job_timeline(job) == []
        job.status.submit_time = 1.0
        job.status.start_time = 2.0
        assert [n for n, _ in job_timeline(job)] == ["submit -> replicas launched"]


class TestGraftEntry:
    def test_entry_traces(self):
        import jax

        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.eval_shape(fn, *args)
        # Flagship LM (llama 0.3b): logits [batch, seq, vocab].
        assert out.shape == (4, 1024, 32000)

    @pytest.mark.slow
    def test_dryrun_multichip_8(self, capsys):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
        out = capsys.readouterr().out
        assert "[dryrun] ok" in out and "dp=1,fsdp=2,sp=2,tp=2" in out
        assert "attn=ring" in out


class TestBenchArtifactContract:
    """Round-5 driver-artifact contract (VERDICT r4 Weak #1): the FINAL
    stdout line must be a compact JSON summary that survives the
    driver's bounded tail window. Round 4's 4.3 KB single line did not,
    and the round's headline numbers were lost to the record."""

    # Worst-case full-detail dict: every block present, floats at full
    # precision, all round-5 serving fields populated.
    FULL = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": 44983.123456789,
        "unit": "tokens/sec/chip",
        "vs_baseline": 1.1085123,
        "config": "0.3b",
        "seq_len": 4096,
        "final_loss": 5.84321098765,
        "mfu": {
            "model_tflops_per_sec": 103.4,
            "vs_peak_pct": 52.5,
            "vs_sustained_matmul_pct": 63.123456,
        },
        "resnet": {
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 2706.987654321,
            "unit": "images/sec/chip",
            "vs_baseline": 1.0149876,
            "mfu": {
                "model_tflops_per_sec": 33.3,
                "vs_peak_pct": 16.9,
                "vs_sustained_matmul_pct": 20.3123,
            },
        },
        "llama_real_data": {
            "metric": "llama_train_real_data_tokens_per_sec_per_chip",
            "value": 56969.123,
            "unit": "tokens/sec/chip",
            "data": "repo source+docs, byte-level, 90/10 held-out split",
            "final_loss": 2.123456789,
            "eval_loss": 2.4123456789,
            "chance_loss": 5.545,
            "learned": True,
        },
        "llama_1b_scale": {
            "metric": "scale_llama_train_tokens_per_sec_per_chip",
            "value": 16256.123,
            "unit": "tokens/sec/chip",
            "config": "1b",
            "params_m": 1100.123,
            "seq_len": 4096,
            "mfu": {
                "model_tflops_per_sec": 124.0,
                "vs_peak_pct": 63.0,
                "vs_sustained_matmul_pct": 75.6123,
            },
        },
        "moe": {
            "metric": "moe_llama_train_tokens_per_sec_per_chip",
            "value": 52642.9,
            "unit": "tokens/sec/chip",
            "n_experts": 8,
            "moe_dispatch": "sparse",
            "moe_top_k": 2,
            "params_m": 1500.1,
            "active_params_m": 500.2,
            "final_loss": 6.1234,
            "mfu": {
                "model_tflops_per_sec": 76.4,
                "vs_peak_pct": 38.8,
                "vs_sustained_matmul_pct": 46.6123,
            },
        },
        "serving_decode": {
            "metric": "serving_decode_tokens_per_sec_per_chip",
            "value": 2141.62345,
            "unit": "tokens/sec/chip",
            "config": "1b",
            "batch": 8,
            "max_decode_len": 4096,
            "weight_mb": 1234.5,
            "quantize": "int8 weights + int8 kv",
            "fp_tokens_per_sec_per_chip": 969.1234,
            "int8_stack_speedup": 2.2098765,
            "vs_baseline": 0.9956789,
            "quality": {"fp_eval_loss": 2.41, "int8_eval_loss": 2.43},
            "ttft_ms_p50": 181.234567,
            "ttft_ms_p99": 423.456789,
            "tpot_ms_p50": 3.73456789,
            "tpot_ms_p99": 5.91234567,
        },
        "bert": {
            "metric": "bert_base_seqs_per_sec_per_chip",
            "value": 1250.123,
            "unit": "seqs/sec/chip",
            "mfu": {
                "model_tflops_per_sec": 107.0,
                "vs_peak_pct": 54.3,
                "vs_sustained_matmul_pct": 65.2123,
            },
        },
        "vit": {
            "metric": "vit_b16_images_per_sec_per_chip",
            "value": 882.123,
            "unit": "images/sec/chip",
            "mfu": {
                "model_tflops_per_sec": 46.6,
                "vs_peak_pct": 23.6,
                "vs_sustained_matmul_pct": 28.4123,
            },
        },
        "schedule_to_first_step_s": {
            "cold": 11.234,
            "warm": 1.297,
            "cold_phases": {
                "submit_to_launch_s": 0.123,
                "launch_to_main_s": 0.456,
                "rendezvous_s": 0.01,
                "import_jax_s": 2.1,
                "client_init_s": 3.2,
                "compile_s": 4.5,
                "first_exec_s": 0.9,
            },
            "warm_phases": {
                "submit_to_launch_s": 0.1,
                "launch_to_main_s": 0.4,
                "rendezvous_s": 0.01,
                "import_jax_s": 0.3,
                "client_init_s": 0.15,
                "compile_s": 0.3,
                "first_exec_s": 0.05,
            },
        },
    }

    def test_compact_worst_case_fits_tail_window(self):
        import json

        import bench

        line = json.dumps(bench.compact(self.FULL))
        assert len(line.encode()) <= bench.COMPACT_MAX_BYTES, len(line)
        c = json.loads(line)
        # The round-over-round trackers must survive compaction.
        assert c["value"] == pytest.approx(44983.1235)
        assert c["vs_baseline"] == pytest.approx(1.1085)
        assert c["mfu_pct"] == pytest.approx(63.123456)
        assert c["resnet"]["vs_baseline"] == pytest.approx(1.015)
        assert c["serving"]["vs_baseline"] == pytest.approx(0.9957)
        assert c["serving"]["int8_stack_speedup"] == pytest.approx(2.2099)
        assert c["serving"]["ttft_ms_p50"] == pytest.approx(181.2346)
        assert c["serving"]["tpot_ms_p99"] == pytest.approx(5.9123)
        assert c["serving"]["quality"] == {
            "fp_eval_loss": 2.41, "int8_eval_loss": 2.43,
        }
        assert c["real_data"]["learned"] is True
        assert c["real_data"]["eval_loss"] == pytest.approx(2.4123)
        assert c["scale_1b"]["mfu_pct"] == pytest.approx(75.6123)
        assert c["moe"]["mfu_pct"] == pytest.approx(46.6123)
        assert c["schedule_to_first_step_s"] == {"cold": 11.234, "warm": 1.297}
        assert c["detail"] == "BENCH_DETAIL.json"
        # Phase breakdowns are detail, not trackers — they must NOT ride.
        assert "cold_phases" not in json.dumps(c)

    def test_compact_resnet_led_fallback(self):
        """If the LM leg failed, the artifact is resnet-led; compact
        must still produce a valid tracked line."""
        import json

        import bench

        out = {
            "metric": "resnet50_images_per_sec_per_chip",
            "value": 2706.9,
            "unit": "images/sec/chip",
            "vs_baseline": 1.015,
        }
        c = bench.compact(out)
        assert c["value"] == 2706.9 and c["vs_baseline"] == 1.015
        assert len(json.dumps(c).encode()) <= bench.COMPACT_MAX_BYTES

    def test_compact_degrades_on_pathological_values(self):
        """A huge leaked string can't break the line: the CORRUPT block
        drops first (largest-first eviction), the cap holds, and every
        healthy tracker survives — even when the corruption lands in an
        early-inserted block like resnet."""
        import json

        import bench

        for victim in ("vit", "resnet"):
            out = dict(self.FULL)
            out[victim] = dict(out[victim], unit="x" * 5000)
            c = bench.compact(out)
            assert len(json.dumps(c).encode()) <= bench.COMPACT_MAX_BYTES
            assert c["value"] == pytest.approx(44983.1235)
            assert victim not in c  # the culprit was evicted...
            # ...and the healthy trackers were not.
            assert c["serving"]["vs_baseline"] == pytest.approx(0.9957)
            assert c["schedule_to_first_step_s"]["warm"] == 1.297

    @pytest.mark.slow
    def test_main_final_stdout_line_is_compact(self, tmp_path):
        """End-to-end: `python bench.py --smoke` must end stdout with a
        parseable line under the cap, and write the detail sidecar."""
        import json
        import os
        import subprocess
        import sys
        from pathlib import Path

        root = Path(__file__).resolve().parent.parent
        detail = tmp_path / "detail.json"
        env = dict(os.environ, TPUJOB_BENCH_DETAIL=str(detail))
        proc = subprocess.run(
            [
                sys.executable, str(root / "bench.py"), "--smoke",
                "--steps", "2", "--warmup", "1", "--no-latency",
            ],
            cwd=root, env=env, capture_output=True, text=True, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        last = proc.stdout.strip().splitlines()[-1]
        assert len(last.encode()) <= 2000  # the driver's tail window
        c = json.loads(last)
        assert c["unit"] == "tokens/sec/chip" and c["value"] > 0
        assert c["resnet"]["value"] > 0
        # The sidecar holds the full detail, including what compaction
        # dropped (mfu sub-dict, final_loss, ...).
        full = json.loads(detail.read_text())
        assert full["metric"] == c["metric"]
        assert set(full["mfu"]) == {
            "model_tflops_per_sec", "vs_peak_pct", "vs_sustained_matmul_pct",
        }
