"""ResNet + bench + driver-entry tests on the virtual CPU mesh."""

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401


class TestResNetModel:
    def test_forward_shapes_and_dtype(self):
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.models.resnet import ResNet

        model = ResNet(stage_sizes=[1, 1], num_filters=8, num_classes=10)
        variables = model.init(
            jax.random.key(0), jnp.zeros((1, 32, 32, 3)), train=False
        )
        logits = model.apply(
            variables, jnp.zeros((4, 32, 32, 3)), train=False
        )
        assert logits.shape == (4, 10)
        assert logits.dtype == jnp.float32  # head stays f32 for stable loss
        assert "batch_stats" in variables

    def test_bf16_bn_stats_mode_trains_finite(self):
        """The experimental bn_f32_stats=False path (bf16 BN reductions,
        BASELINE.md A/B note) must produce finite logits and stats."""
        import jax
        import jax.numpy as jnp
        import numpy as np

        from pytorch_operator_tpu.models.resnet import ResNet

        model = ResNet(
            stage_sizes=[1, 1], num_filters=8, num_classes=10, bn_f32_stats=False
        )
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((4, 32, 32, 3)),
            jnp.float32,
        )
        variables = model.init(jax.random.key(0), x, train=False)
        logits, updates = model.apply(
            variables, x, train=True, mutable=["batch_stats"]
        )
        assert bool(jnp.isfinite(logits).all())
        mean_leaf = jax.tree.leaves(updates["batch_stats"])[0]
        assert mean_leaf.dtype == jnp.bfloat16  # stats really are bf16
        assert all(
            bool(jnp.isfinite(leaf.astype(jnp.float32)).all())
            for leaf in jax.tree.leaves(updates["batch_stats"])
        )

    def test_train_step_updates_params_and_stats(self):
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_operator_tpu.models.resnet import ResNet
        from pytorch_operator_tpu.parallel import make_mesh
        from pytorch_operator_tpu.workloads.resnet_bench import (
            build_train_state,
            make_train_step,
        )

        model = ResNet(stage_sizes=[1], num_filters=8, num_classes=10, dtype=jnp.float32)
        mesh = make_mesh("dp=8")
        params, stats, opt_state, tx = build_train_state(
            model, mesh, lr=0.1, momentum=0.9, seed=0, image_size=16
        )
        step = make_train_step(model, tx)
        bx = jnp.ones((8, 16, 16, 3))
        by = jnp.zeros((8,), jnp.int32)
        p2, s2, o2, loss = step(params, stats, opt_state, bx, by)
        assert np.isfinite(float(loss))
        # params moved
        diffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), params, p2)
        assert max(jax.tree.leaves(diffs)) > 0
        # BN stats moved
        sdiffs = jax.tree.map(lambda a, b: float(jnp.abs(a - b).max()), stats, s2)
        assert max(jax.tree.leaves(sdiffs)) > 0


class TestBench:
    def test_bench_smoke_emits_schema(self, capsys):
        import bench

        result = bench.run(["--smoke", "--steps", "2", "--warmup", "1"])
        # Round-4 shape: the artifact LEADS with the flagship LM (the
        # MFU carrier); ResNet rides as the continuity sub-block.
        assert set(result) == {
            "metric",
            "value",
            "unit",
            "mfu",
            "config",
            "seq_len",
            "final_loss",
            "resnet",
            "schedule_to_first_step_s",
        }
        assert result["value"] > 0
        assert result["unit"] == "tokens/sec/chip"
        assert set(result["mfu"]) == {
            "model_tflops_per_sec",
            "vs_peak_pct",
            "vs_sustained_matmul_pct",
        }
        rn = result["resnet"]
        assert rn["unit"] == "images/sec/chip" and rn["value"] > 0
        assert rn["vs_baseline"] > 0
        # The latency probe runs REAL supervisor jobs even in smoke mode
        # (with a pre-warmed standby, the production daemon config);
        # both phases must come back measured, not None.
        lat = result["schedule_to_first_step_s"]
        assert lat["cold"] > 0 and lat["warm"] > 0

    def test_bench_smoke_no_latency_flag(self):
        import bench

        result = bench.run(
            ["--smoke", "--steps", "2", "--warmup", "1", "--no-latency"]
        )
        assert set(result) == {
            "metric", "value", "unit", "mfu", "config", "seq_len",
            "final_loss", "resnet",
        }

    def test_mfu_math(self):
        import bench

        # 164 TF/s of model FLOPs == 100% of sustained, ~83% of peak.
        m = bench.mfu(164e12)
        assert m["vs_sustained_matmul_pct"] == 100.0
        assert 80 < m["vs_peak_pct"] < 85
        # The LM formula: 6N dominates at short S.
        f = bench.lm_train_flops_per_token(1e9, 16, 1024, 64)
        assert abs(f - (6e9 + 6 * 16 * 64 * 1024)) < 1


class TestDataFileMode:
    def test_trains_from_packed_file(self, tmp_path):
        """Real-data path: distinct per-step batches from the native
        prefetch loader, scanned inside one dispatch."""
        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        out = tmp_path / "syn.bin"
        assert pack_main([
            "--out", str(out), "--dataset", "synthetic",
            "--n", "64", "--height", "32", "--width", "32", "--classes", "10",
        ]) == 0
        result = run_benchmark(
            depth=18,
            batch_size=16,
            classes=10,
            steps=4,
            warmup=2,
            data_file=str(out),
            log=lambda *_: None,
        )
        assert result["input"] == "file"
        assert np.isfinite(result["final_loss"])
        assert result["value"] > 0

    def test_labels_exceeding_classes_rejected(self, tmp_path):
        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        out = tmp_path / "syn.bin"
        pack_main([
            "--out", str(out), "--dataset", "synthetic",
            "--n", "32", "--height", "16", "--width", "16", "--classes", "10",
        ])
        with pytest.raises(ValueError, match="classes"):
            run_benchmark(
                depth=18, batch_size=16, classes=4, steps=2, warmup=1,
                data_file=str(out), log=lambda *_: None,
            )

    def test_bad_label_beyond_first_chunk_rejected(self, tmp_path):
        """ADVICE r2: the old first-chunk latch sampled only the first
        drawn batches; a bad label in a later record one-hotted to a zero
        row and silently deflated the loss. The whole-file field_range
        scan must reject it up front — before any batch is drawn."""
        import numpy as np

        from pytorch_operator_tpu.data import pack_arrays
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        n = 64
        x = np.random.default_rng(0).random((n, 16, 16, 3), np.float32)
        y = np.full((n,), 3, np.int32)
        y[-1] = 10  # out of range, and outside any first-chunk sample
        out = tmp_path / "bad-tail.bin"
        pack_arrays(out, {"x": x, "y": y})
        with pytest.raises(ValueError, match="classes"):
            run_benchmark(
                depth=18, batch_size=16, classes=10, steps=2, warmup=1,
                data_file=str(out), log=lambda *_: None,
            )
        y[-1] = -1  # negative ids are just as silent in one_hot
        out2 = tmp_path / "bad-neg.bin"
        pack_arrays(out2, {"x": x, "y": y})
        with pytest.raises(ValueError, match="classes"):
            run_benchmark(
                depth=18, batch_size=16, classes=10, steps=2, warmup=1,
                data_file=str(out2), log=lambda *_: None,
            )

    def test_file_smaller_than_batch_rejected(self, tmp_path):
        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        out = tmp_path / "tiny.bin"
        pack_main([
            "--out", str(out), "--dataset", "synthetic",
            "--n", "8", "--height", "16", "--width", "16",
        ])
        with pytest.raises(ValueError, match="records < global batch"):
            run_benchmark(
                depth=18, batch_size=64, steps=2, warmup=1,
                data_file=str(out), log=lambda *_: None,
            )


class TestProfileTrace:
    def test_profile_dir_writes_trace(self, tmp_path):
        from pytorch_operator_tpu.workloads.resnet_bench import run_benchmark

        prof = tmp_path / "trace"
        run_benchmark(
            depth=18,
            batch_size=8,
            image_size=32,
            classes=10,
            steps=2,
            warmup=1,
            profile_dir=str(prof),
            log=lambda *_: None,
        )
        # jax.profiler writes <dir>/plugins/profile/<ts>/*.xplane.pb
        assert list(prof.rglob("*.xplane.pb")), "no profiler trace written"


class TestTimeline:
    def test_job_timeline_spans(self):
        from pytorch_operator_tpu.api.types import TPUJob
        from pytorch_operator_tpu.controller.supervisor import job_timeline

        job = TPUJob.from_dict({"metadata": {"name": "t"}})
        job.status.submit_time = 100.0
        job.status.start_time = 101.0
        job.status.first_step_time = 105.0
        job.status.completion_time = 110.0
        spans = dict(job_timeline(job))
        assert spans["submit -> replicas launched"] == pytest.approx(1.0)
        assert spans["launch -> first step"] == pytest.approx(4.0)
        assert spans["first step -> finished"] == pytest.approx(5.0)
        assert spans["total (submit -> finished)"] == pytest.approx(10.0)

    def test_job_timeline_partial(self):
        from pytorch_operator_tpu.api.types import TPUJob
        from pytorch_operator_tpu.controller.supervisor import job_timeline

        job = TPUJob.from_dict({"metadata": {"name": "t"}})
        assert job_timeline(job) == []
        job.status.submit_time = 1.0
        job.status.start_time = 2.0
        assert [n for n, _ in job_timeline(job)] == ["submit -> replicas launched"]


class TestGraftEntry:
    def test_entry_traces(self):
        import jax

        import __graft_entry__ as g

        fn, args = g.entry()
        out = jax.eval_shape(fn, *args)
        # Flagship LM (llama 0.3b): logits [batch, seq, vocab].
        assert out.shape == (4, 1024, 32000)

    def test_dryrun_multichip_8(self, capsys):
        import __graft_entry__ as g

        g.dryrun_multichip(8)
        out = capsys.readouterr().out
        assert "[dryrun] ok" in out and "dp=1,fsdp=2,sp=2,tp=2" in out
        assert "attn=ring" in out
