"""The cached store's invalidation and exactly-once contracts.

The dirty-tracking cache (controller/store.py) must never trade
correctness for the I/O win: external edits are picked up via
``rescan``/``reload`` with the cache armed, dirty writes still land,
and the rename-claimed markers stay exactly-once under two supervisors
sharing a state dir — with and without the scandir snapshot armed.
"""

from __future__ import annotations

import json
import threading

import pytest

from pytorch_operator_tpu.controller.store import JobStore, key_to_fs
from tests.testutil import new_job


def job_path(d, key):
    return d / (key_to_fs(key) + ".json")


class TestDirtyTracking:
    def test_idle_update_skips_the_write(self, tmp_path):
        store = JobStore(persist_dir=tmp_path / "jobs")
        key = store.add(new_job(name="idle"))
        p = job_path(tmp_path / "jobs", key)
        before = p.stat().st_mtime_ns, store.io.writes
        for _ in range(5):
            store.update(store.get(key))
        assert (p.stat().st_mtime_ns, store.io.writes) == before
        assert store.io.writes_skipped >= 5

    def test_real_change_still_lands_on_disk(self, tmp_path):
        store = JobStore(persist_dir=tmp_path / "jobs")
        key = store.add(new_job(name="dirty"))
        job = store.get(key)
        job.status.restart_count = 7
        job.touch()  # the mutator contract (set_condition does this)
        store.update(job)
        on_disk = json.loads(job_path(tmp_path / "jobs", key).read_text())
        assert on_disk["status"]["restart_count"] == 7

    def test_set_condition_marks_dirty_without_explicit_touch(self, tmp_path):
        """The central mutators bump the generation themselves — the
        reconciler never calls touch() around set_condition."""
        from pytorch_operator_tpu.api.types import ConditionType

        store = JobStore(persist_dir=tmp_path / "jobs")
        key = store.add(new_job(name="cond"))
        job = store.get(key)
        job.set_condition(ConditionType.RUNNING, reason="T", message="t")
        store.update(job)
        on_disk = json.loads(job_path(tmp_path / "jobs", key).read_text())
        assert on_disk["status"]["conditions"], "condition change not persisted"

    def test_clean_check_is_o1_no_serialization(self, tmp_path):
        """THE control-plane follow-on pin (ROADMAP): an idle update must
        not even call to_dict() — the clean check is one generation
        compare, so a 10k-job fleet's steady pass serializes nothing."""
        store = JobStore(persist_dir=tmp_path / "jobs")
        key = store.add(new_job(name="o1"))
        base = store.io.serializations
        for _ in range(25):
            store.update(store.get(key))
        assert store.io.serializations == base
        assert store.io.writes_skipped >= 25
        # A touched-but-identical job pays ONE serialization (content
        # dedupe), then returns to the O(1) path.
        job = store.get(key)
        job.touch()
        store.update(job)
        assert store.io.serializations == base + 1
        store.update(store.get(key))
        assert store.io.serializations == base + 1

    def test_new_object_for_known_key_bypasses_generation_gate(self, tmp_path):
        """A FRESH object handed to update() (apply/failover flows) must
        not be mistaken for clean just because its generation matches
        the recorded one."""
        from tests.testutil import new_job as make

        store = JobStore(persist_dir=tmp_path / "jobs")
        key = store.add(make(name="swap"))
        replacement = make(name="swap")
        replacement.status.restart_count = 9  # same generation (0), new bytes
        store.update(replacement)
        on_disk = json.loads(job_path(tmp_path / "jobs", key).read_text())
        assert on_disk["status"]["restart_count"] == 9

    def test_loaded_store_does_not_rewrite_clean_jobs(self, tmp_path):
        store = JobStore(persist_dir=tmp_path / "jobs")
        key = store.add(new_job(name="reload"))
        # A fresh store over the same dir (daemon restart): its first
        # no-op update must not touch the file.
        store2 = JobStore(persist_dir=tmp_path / "jobs")
        p = job_path(tmp_path / "jobs", key)
        mtime = p.stat().st_mtime_ns
        store2.update(store2.get(key))
        assert p.stat().st_mtime_ns == mtime


class TestExternalInvalidation:
    def test_rescan_discovers_new_files_without_rereading_known(self, tmp_path):
        d = tmp_path / "jobs"
        owner = JobStore(persist_dir=d)
        owner.add(new_job(name="known"))
        # Another process (CLI submit) lands a new job file.
        other = JobStore(persist_dir=d)
        other.add(new_job(name="fresh"))
        reads_before = owner.io.reads
        new = owner.rescan()
        assert new == ["default/fresh"]
        # Exactly one file read: the unknown one. Known keys resolve by
        # filename against the cache.
        assert owner.io.reads == reads_before + 1

    def test_reload_picks_up_external_edit_with_cache_armed(self, tmp_path):
        d = tmp_path / "jobs"
        observer = JobStore(persist_dir=d)
        key = observer.add(new_job(name="watched"))
        # External writer (the owning daemon in another process) bumps
        # the restart count on disk.
        writer = JobStore(persist_dir=d)
        job = writer.get(key)
        job.status.restart_count = 3
        job.touch()
        writer.update(job)
        assert observer.get(key).status.restart_count == 0  # cached
        assert observer.reload(key).status.restart_count == 3
        # And the refreshed clean snapshot keeps dirty tracking truthful:
        # a no-op update after reload must not rewrite the file.
        mtime = job_path(d, key).stat().st_mtime_ns
        observer.update(observer.get(key))
        assert job_path(d, key).stat().st_mtime_ns == mtime

    def test_update_after_reload_persists_new_changes(self, tmp_path):
        d = tmp_path / "jobs"
        store = JobStore(persist_dir=d)
        key = store.add(new_job(name="evolve"))
        store.reload(key)
        job = store.get(key)
        job.status.restart_count = 1
        job.touch()
        store.update(job)
        assert (
            json.loads(job_path(d, key).read_text())["status"]["restart_count"]
            == 1
        )


class TestMarkerExactlyOnce:
    @pytest.mark.parametrize("snapshot", [False, True])
    def test_scale_marker_claimed_exactly_once_by_two_supervisors(
        self, tmp_path, snapshot
    ):
        """Two stores over one dir race to claim the same scale marker;
        rename-claim must hand it to exactly one — whether the candidate
        list came from a fresh glob or the rescan snapshot (which may be
        stale by claim time)."""
        d = tmp_path / "jobs"
        a, b = JobStore(persist_dir=d), JobStore(persist_dir=d)
        for round_ in range(10):
            key = f"default/race-{round_}"
            a.mark_scale(key, 4)
            if snapshot:
                a.rescan()
                b.rescan()
            results = {}
            barrier = threading.Barrier(2)

            def claim(store, tag):
                barrier.wait()
                results[tag] = store.take_scale_markers()

            ts = [
                threading.Thread(target=claim, args=(a, "a")),
                threading.Thread(target=claim, args=(b, "b")),
            ]
            for t in ts:
                t.start()
            for t in ts:
                t.join(10)
            claims = results["a"] + results["b"]
            assert claims == [(key, 4)], claims

    def test_marker_written_after_snapshot_survives_to_next_pass(self, tmp_path):
        d = tmp_path / "jobs"
        store = JobStore(persist_dir=d)
        store.rescan()  # snapshot armed, no markers yet
        store.mark_suspend("default/late", True)
        # This pass's snapshot predates the marker: not claimed...
        assert store.take_suspend_markers() == []
        # ...but the next pass's snapshot picks it up — never lost.
        store.rescan()
        assert store.take_suspend_markers() == [("default/late", True)]

    def test_take_without_rescan_still_globs(self, tmp_path):
        store = JobStore(persist_dir=tmp_path / "jobs")
        store.mark_scale("default/solo", 2)
        assert store.take_scale_markers() == [("default/solo", 2)]


class TestLegacyMode:
    def test_cache_false_reproduces_precache_io(self, tmp_path):
        d = tmp_path / "jobs"
        store = JobStore(persist_dir=d, cache=False)
        key = store.add(new_job(name="old-school"))
        writes = store.io.writes
        store.update(store.get(key))  # no-op update still writes
        assert store.io.writes == writes + 1
        reads = store.io.reads
        store.rescan()  # re-reads every file
        assert store.io.reads == reads + 1
