"""RunPolicy.suspend — create-but-don't-run (reference: training-operator
RunPolicy.suspend, the Kueue integration point). Suspending a live job
tears its world down but keeps the job; resuming relaunches it, with the
activeDeadlineSeconds clock reset.
"""

from __future__ import annotations

from pytorch_operator_tpu.api.types import ConditionType, ReplicaPhase, ReplicaType, RunPolicy
from pytorch_operator_tpu.controller.runner import FakeRunner, replica_name
from pytorch_operator_tpu.controller.supervisor import Supervisor
from tests.testutil import new_job


def make_sup():
    return Supervisor(state_dir=None, runner=FakeRunner(), persist=False)


class TestSuspend:
    def test_suspended_job_creates_no_replicas(self):
        sup = make_sup()
        job = new_job(name="s1", workers=1)
        job.spec.run_policy.suspend = True
        key = sup.submit(job)
        sup.sync_once()
        assert sup.runner.list_for_job(key) == []
        j = sup.get(key)
        assert j.has_condition(ConditionType.SUSPENDED)
        assert not j.is_finished()

    def test_suspend_live_job_tears_down_world(self):
        sup = make_sup()
        key = sup.submit(new_job(name="s2", workers=2))
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 3
        j = sup.get(key)
        j.spec.run_policy.suspend = True
        sup.store.update(j)
        sup.sync_once()
        assert sup.runner.list_for_job(key) == []
        j = sup.get(key)
        assert j.has_condition(ConditionType.SUSPENDED)
        assert j.status.start_time is None  # deadline clock reset

    def test_resume_relaunches_and_clears_condition(self):
        sup = make_sup()
        job = new_job(name="s3", workers=1)
        job.spec.run_policy.suspend = True
        key = sup.submit(job)
        sup.sync_once()
        j = sup.get(key)
        j.spec.run_policy.suspend = False
        sup.store.update(j)
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 2
        j = sup.get(key)
        assert not j.has_condition(ConditionType.SUSPENDED)
        assert any(e.reason == "TPUJobResumed" for e in sup.events.for_job(key))
        # Running clears Suspended for good once the master is up.
        sup.runner.set_all_running(key)
        sup.sync_once()
        assert sup.get(key).has_condition(ConditionType.RUNNING)

    def test_suspended_job_can_still_complete_normally_after_resume(self):
        sup = make_sup()
        job = new_job(name="s4", workers=0)
        job.spec.run_policy.suspend = True
        key = sup.submit(job)
        sup.sync_once()
        j = sup.get(key)
        j.spec.run_policy.suspend = False
        sup.store.update(j)
        sup.sync_once()
        sup.runner.set_all_running(key)
        sup.runner.set_phase(
            replica_name(key, ReplicaType.MASTER, 0),
            ReplicaPhase.SUCCEEDED,
            exit_code=0,
        )
        sup.sync_once()
        assert sup.get(key).is_succeeded()

    def test_suspend_markers_cross_process(self, tmp_path):
        sup = Supervisor(state_dir=tmp_path, runner=FakeRunner(), persist=True)
        key = sup.submit(new_job(name="s5", workers=0))
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 1
        # Another process (the CLI) leaves a suspend marker.
        sup.store.mark_suspend(key, True)
        sup.process_suspend_markers()
        sup.sync_once()
        assert sup.runner.list_for_job(key) == []
        sup.store.mark_suspend(key, False)
        sup.process_suspend_markers()
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 1

    def test_round_trip(self):
        rp = RunPolicy(suspend=True)
        assert RunPolicy.from_dict(rp.to_dict()).suspend is True
        assert RunPolicy.from_dict({}).suspend is False

    def test_pytorchjob_suspend_converts(self):
        from pytorch_operator_tpu.api import loads_job

        job = loads_job(
            """
kind: PyTorchJob
metadata: {name: kueue}
spec:
  runPolicy:
    suspend: true
    schedulingPolicy: {scheduleTimeoutSeconds: 300}
  pytorchReplicaSpecs:
    Master:
      template:
        spec:
          containers: [{name: pytorch, command: [sh, -c, "exit 0"]}]
"""
        )
        assert job.spec.run_policy.suspend is True
        assert (
            job.metadata.annotations["tpujob.dev/converted-schedule-timeout-seconds"]
            == "300"
        )
