"""ops.flop_count: the jaxpr-walking semantic FLOP counter.

Exists because XLA cost_analysis and jax.experimental.roofline count a
scan body ONCE (verified on this install), so neither can compare
pipelined programs whose compute lives inside the schedule scan.
"""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.ops.flop_count import count_flops


class TestFlopCount:
    def test_dot_general(self):
        import jax.numpy as jnp

        fc = count_flops(lambda a, b: a @ b, jnp.zeros((8, 16)), jnp.zeros((16, 4)))
        assert fc.by_primitive["dot_general"] == 2 * 8 * 4 * 16

    def test_scan_multiplies_by_length(self):
        import jax
        import jax.numpy as jnp

        w = jnp.zeros((16, 16))

        def f(x):
            def body(c, _):
                return jnp.tanh(c @ w), None

            y, _ = jax.lax.scan(body, x, None, length=10)
            return y

        fc = count_flops(f, jnp.zeros((4, 16)))
        assert fc.by_primitive["dot_general"] == 10 * 2 * 4 * 16 * 16

    def test_shard_map_multiplies_by_manual_devices(self):
        import jax
        import jax.numpy as jnp
        from pytorch_operator_tpu.jaxcompat import shard_map
        from jax.sharding import PartitionSpec as P

        from pytorch_operator_tpu.parallel import make_mesh

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])

        def f(w, x):
            def body(wl, xl):
                return jax.lax.psum(xl @ wl[0], "pp")

            return shard_map(
                body, mesh=mesh, in_specs=(P("pp"), P()), out_specs=P(),
                axis_names={"pp"},
            )(w, x)

        fc = count_flops(f, jnp.zeros((4, 16, 8)), jnp.zeros((2, 16)))
        # Each of the 4 manual devices runs one [2,16]@[16,8] matmul.
        assert fc.by_primitive["dot_general"] == 4 * 2 * 2 * 8 * 16
        # Collectives are communication, not FLOPs.
        assert "psum" not in fc.by_primitive

    def test_cond_takes_max_branch(self):
        import jax
        import jax.numpy as jnp

        w = jnp.zeros((16, 16))

        def f(x):
            return jax.lax.cond(
                x.sum() > 0, lambda a: (a @ w).sum(), lambda a: a.sum(), x
            )

        fc = count_flops(f, jnp.ones((4, 16)))
        assert fc.by_primitive["dot_general"] == 2 * 4 * 16 * 16

    def test_remat_backward_counts_recompute(self):
        """grad of a checkpointed fn recomputes the forward: the counted
        dot FLOPs must be fwd + recompute + 2x bwd = 4 matmul units (vs 3
        without remat)."""
        import jax
        import jax.numpy as jnp

        unit = 2 * 4 * 16 * 16

        def mk(remat):
            def f(w, x):
                g = lambda a: jnp.tanh(a @ w).sum()  # noqa: E731
                if remat:
                    g = jax.checkpoint(g)
                return g(x)

            return jax.grad(f, argnums=(0, 1))

        args = (jnp.zeros((16, 16)), jnp.zeros((4, 16)))
        no_remat = count_flops(mk(False), *args).by_primitive["dot_general"]
        with_remat = count_flops(mk(True), *args).by_primitive["dot_general"]
        assert no_remat == 3 * unit
        assert with_remat == 4 * unit


class TestPipelineFlopParity:
    """THE round-4 guard (VERDICT Missing #2 / Next #1): the 1F1B llama
    step's TOTAL semantic FLOPs must sit within ~1.1x of both the GPipe
    step and the unpipelined reference on the same fat-head config.
    Before the vocab-parallel loss tail + stored-residual backward, this
    ratio was ~2.4x at 0.3b head fractions (the loss tail ran P-fold and
    the backward re-ran every stage forward)."""

    @pytest.mark.slow
    def test_1f1b_total_flops_within_1p15_of_gpipe(self):
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_operator_tpu.models.llama import (
            Llama, forward_pp, llama_tiny, train_value_and_grad_pp,
        )
        from pytorch_operator_tpu.parallel import make_mesh

        # Fat head on purpose: vocab-dominant dims make loss-tail
        # duplication show up at full strength (head ~= half the FLOPs).
        cfg = llama_tiny(vocab_size=4096, d_model=64, n_layers=4, remat=True)
        model = Llama(cfg)
        B, S, M, PP = 64, 32, 64, 4
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, cfg.vocab_size, (B, S)),
            jnp.int32,
        )
        params = model.init(jax.random.key(0), tokens[:1])["params"]
        mesh = make_mesh(f"pp={PP}", devices=jax.devices()[:PP])

        def seq_loss(p, toks):
            logits = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], toks[:, 1:]
            ).mean()

        def gpipe_loss(p, toks):
            logits = forward_pp(model, p, toks, mesh=mesh, microbatches=M)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], toks[:, 1:]
            ).mean()

        f_seq = count_flops(jax.value_and_grad(seq_loss), params, tokens).total
        f_gp = count_flops(jax.value_and_grad(gpipe_loss), params, tokens).total
        f_1f1b = count_flops(
            lambda p, t: train_value_and_grad_pp(
                model, p, t, mesh=mesh, microbatches=M
            ),
            params,
            tokens,
        ).total

        # Analytic floor: the static schedule runs (M+2P-2)/M ticks per
        # useful microbatch = 1.094 here; measured 1.087/1.059 at last
        # tuning. Thresholds leave noise headroom without admitting any
        # P-fold regression (which lands at 2.4x+).
        assert f_1f1b <= 1.15 * f_gp, (f_1f1b / 1e9, f_gp / 1e9)
        assert f_1f1b <= 1.20 * f_seq, (f_1f1b / 1e9, f_seq / 1e9)
        # And GPipe itself must stay near the sequential reference.
        assert f_gp <= 1.10 * f_seq, (f_gp / 1e9, f_seq / 1e9)
