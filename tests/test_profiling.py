"""Profile-report tool (pytorch_operator_tpu/profiling.py).

A real workload writes a jax.profiler trace; the tool must parse the
xplane.pb and produce a self-time breakdown whose busy total does not
exceed the step span (the nesting bug it exists to avoid is
double-counting scan bodies inside their `while`).
"""

import subprocess
import sys

import pytest

import tests.jaxenv  # noqa: F401

from pytorch_operator_tpu import profiling
from pytorch_operator_tpu.workloads import llama_train


@pytest.fixture(scope="module")
def trace_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("prof")
    llama_train.run(
        config="tiny", batch_size=4, seq_len=32, steps=4, warmup=1,
        profile_dir=str(d), log=lambda *_: None,
    )
    return d


def test_report_parses_cpu_trace(trace_dir):
    report = profiling.device_report(trace_dir, device_substr="CPU")
    assert report is not None
    assert report.get("busy_s", 0) > 0
    assert report["categories"], report
    # Self-time accounting: total busy is a partition of the trace, so
    # the per-category sum equals busy (no nested double counting).
    total = sum(c["pct_of_busy"] for c in report["categories"])
    assert total == pytest.approx(100.0, abs=0.5), total


def test_report_missing_device_returns_none(trace_dir):
    assert profiling.device_report(trace_dir, device_substr="NOPE") is None


def test_cli_human_and_json(trace_dir):
    out = subprocess.run(
        [sys.executable, "-m", "pytorch_operator_tpu.profiling",
         str(trace_dir), "--device", "CPU", "--top", "5"],
        capture_output=True, text=True,
    )
    assert out.returncode == 0, out.stderr
    assert "by op category" in out.stdout
    j = subprocess.run(
        [sys.executable, "-m", "pytorch_operator_tpu.profiling",
         str(trace_dir), "--device", "CPU", "--json", "--top", "3"],
        capture_output=True, text=True,
    )
    assert j.returncode == 0, j.stderr
    import json

    data = json.loads(j.stdout)
    assert len(data["top_ops"]) <= 3


def test_missing_dir_errors_cleanly(tmp_path):
    rc = profiling.main([str(tmp_path / "nope")])
    assert rc == 1
