"""Test configuration.

Sets env so tests (and the subprocess workloads they launch) use the JAX CPU
backend with 8 virtual host devices, exercising real multi-device code paths
without TPU hardware (SURVEY.md §4 "Rebuild translation").

jax itself is NOT imported here — control-plane tests stay jax-free. Test
modules that use jax in-process must ``import tests.jaxenv`` first, which
forces the platform via jax.config (the env var alone is overridden by this
environment's site customization; XLA_FLAGS via env IS honored because it is
read at client creation).
"""

import os

# Read at CPU client creation — must be set before any backend is built.
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["TPUJOB_PLATFORM"] = "cpu"

import pytest  # noqa: E402


@pytest.fixture
def tmp_state_dir(tmp_path):
    """A fresh supervisor state directory."""
    d = tmp_path / "tpujob-state"
    d.mkdir()
    return d
