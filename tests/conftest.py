"""Test configuration.

Forces the JAX CPU backend with 8 virtual host devices BEFORE any jax import,
so sharding/mesh tests exercise real multi-device code paths without TPU
hardware (SURVEY.md §4 "Rebuild translation"). Control-plane tests never
import jax at all.
"""

import os

# Must happen before jax is imported anywhere in the test process.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep XLA compilation single-threaded-ish on the 1-core CI box.
os.environ.setdefault("JAX_ENABLE_COMPILATION_CACHE", "false")

import pytest  # noqa: E402


@pytest.fixture
def tmp_state_dir(tmp_path):
    """A fresh supervisor state directory."""
    d = tmp_path / "tpujob-state"
    d.mkdir()
    return d
