"""Pre-warmed standby replicas (controller/standby.py + the runner's
warm-create path) — the schedule-to-first-step accelerator (VERDICT r2
Weak #3).

Covers the pool lifecycle (ready/replenish/death/leak), the full
job-through-a-standby path (env wholesale, log redirect, exit-capture
file, success AND failure codes), fallback to cold spawn, supervisor
integration, and adoption semantics (a standby-run replica is a normal
replica: pid IS the workload).
"""

from __future__ import annotations

import os
import time

from pytorch_operator_tpu.api.types import (
    ProcessTemplate,
    ReplicaPhase,
    ReplicaType,
)
from pytorch_operator_tpu.controller.runner import SubprocessRunner, replica_name
from pytorch_operator_tpu.controller.standby import StandbyPool
from pytorch_operator_tpu.controller.supervisor import Supervisor
from tests.testutil import new_job

import pytest

# Fast-lane exclusion (-m 'not slow'): standby pool subprocesses.
pytestmark = pytest.mark.slow

KEY = "default/warm"


def wait_for(pred, timeout=60.0, interval=0.05):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(interval)
    return False


def probe_template(**env):
    return ProcessTemplate(module="tests.standby_probe", env=dict(env))


def pid_gone(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        # Alive or zombie; zombies count as gone for leak purposes.
        with open(f"/proc/{pid}/stat", "rb") as f:
            raw = f.read()
        return raw[raw.rfind(b")") + 2 :].split()[0] == b"Z"
    except (OSError, ProcessLookupError):
        return True


class TestStandbyPool:
    def test_spawn_ready_take_replenish(self, tmp_path):
        pool = StandbyPool(tmp_path, size=1)
        pool.replenish()
        try:
            assert wait_for(lambda: pool.ready_count() == 1), "never ready"
            taken = pool.take()
            assert taken is not None
            sid, proc = taken
            assert pool.ready_count() == 0  # consumed
            pool.kill(sid, proc)
            pool.replenish()  # tops back up
            assert wait_for(lambda: pool.ready_count() == 1)
        finally:
            pool.shutdown()

    def test_dead_standby_reaped_and_respawned(self, tmp_path):
        pool = StandbyPool(tmp_path, size=1)
        pool.replenish()
        try:
            assert wait_for(lambda: pool.ready_count() == 1)
            (sid, proc), = [next(iter(pool._procs.items()))]
            os.killpg(proc.pid, 9)
            assert wait_for(lambda: proc.poll() is not None)
            pool.replenish()
            assert sid not in pool._procs  # dead one reaped...
            assert wait_for(lambda: pool.ready_count() == 1)  # ...replaced
            assert not (pool.dir / f"{sid}.ready").exists()
        finally:
            pool.shutdown()

    def test_crash_looping_standby_backs_off_and_rotates_one_failure_log(
        self, tmp_path, monkeypatch
    ):
        """A standby that dies before READY must not respawn every pass
        (exponential backoff) nor grow logs/ unboundedly (one rotated
        standby-last-failure.log, per-sid logs removed)."""
        import subprocess
        import sys as _sys
        import time as _time

        def dying_spawn(self):
            sid = f"s{os.getpid()}-{self._counter}"
            self._counter += 1
            log_f = open(self.log_dir / f"standby-{sid}.log", "ab")
            proc = subprocess.Popen(
                [_sys.executable, "-c",
                 "import sys; sys.stderr.write('boom'); sys.exit(3)"],
                stdout=log_f, stderr=subprocess.STDOUT,
                start_new_session=True,
            )
            log_f.close()
            self._procs[sid] = proc
            return True

        monkeypatch.setattr(StandbyPool, "_spawn_one", dying_spawn)
        pool = StandbyPool(tmp_path, size=1)
        try:
            pool.replenish()  # spawns the dying standby
            (sid, proc), = list(pool._procs.items())
            assert wait_for(lambda: proc.poll() is not None)
            pool.replenish()  # reaps -> backoff engaged
            assert pool._fail_streak == 1
            assert pool._not_before > _time.time()
            assert not (pool.log_dir / f"standby-{sid}.log").exists()
            assert (pool.log_dir / "standby-last-failure.log").exists()
            assert "boom" in (
                pool.log_dir / "standby-last-failure.log"
            ).read_text()
            # Backoff holds: no fresh spawn while _not_before is ahead.
            assert pool._procs == {}
            # ...and expires: clearing the gate spawns again.
            pool._not_before = 0.0
            pool.replenish()
            assert len(pool._procs) == 1
        finally:
            pool.shutdown()

    def test_no_log_files_leak_across_lifecycle(self, tmp_path):
        """Clean kills (shutdown) remove per-standby logs."""
        pool = StandbyPool(tmp_path, size=1)
        try:
            pool.replenish()
            assert wait_for(lambda: pool.ready_count() == 1)
        finally:
            pool.shutdown()
        assert list(pool.log_dir.glob("standby-*.log")) == []

    def test_assign_to_dead_standby_returns_false(self, tmp_path):
        pool = StandbyPool(tmp_path, size=1)
        pool.replenish()
        try:
            assert wait_for(lambda: pool.ready_count() == 1)
            sid, proc = pool.take()
            os.killpg(proc.pid, 9)
            assert wait_for(lambda: proc.poll() is not None)
            assert pool.assign(sid, proc, {"module": "x"}) is False
        finally:
            pool.shutdown()

    def test_shutdown_leaves_no_processes(self, tmp_path):
        pool = StandbyPool(tmp_path, size=2)
        pool.replenish()
        assert wait_for(lambda: pool.ready_count() == 2)
        pids = [p.pid for p in pool._procs.values()]
        pool.shutdown()
        assert all(wait_for(lambda: pid_gone(pid), 10) for pid in pids)


class TestWarmCreate:
    def test_job_runs_in_standby_with_env_log_and_exit_capture(self, tmp_path):
        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            standby_pid = next(iter(runner._standby_pool._procs.values())).pid
            t0 = time.time()
            h = runner.create(
                KEY, ReplicaType.MASTER, 0,
                probe_template(PROBE_VAL="hello-warm"), {},
            )
            assert h.pid == standby_pid, "job did not go to the standby"
            assert wait_for(
                lambda: (runner.sync(), runner.get(h.name).is_finished())[1]
            )
            got = runner.get(h.name)
            assert got.phase == ReplicaPhase.SUCCEEDED and got.exit_code == 0
            # Output landed in the replica's log (fd-level redirect).
            log = (tmp_path / "logs").glob("*warm-master-0.log")
            text = "\n".join(p.read_text() for p in log)
            assert "probe-env hello-warm" in text
            # Exit-capture file written (adoption protocol parity).
            assert runner._read_exit_file(h.name) == 0
            # And it was warm: no interpreter+import tax on this path.
            assert time.time() - t0 < 30
        finally:
            runner.shutdown()

    def test_failure_exit_code_propagates(self, tmp_path):
        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            h = runner.create(
                KEY, ReplicaType.MASTER, 0, probe_template(PROBE_EXIT="7"), {}
            )
            assert wait_for(
                lambda: (runner.sync(), runner.get(h.name).is_finished())[1]
            )
            got = runner.get(h.name)
            assert got.phase == ReplicaPhase.FAILED and got.exit_code == 7
        finally:
            runner.shutdown()

    def test_cold_and_warm_replicas_see_identical_environments(
        self, tmp_path, monkeypatch
    ):
        """VERDICT r3 Weak #6: the standby's env-wholesale apply
        (os.environ.clear + update) must not drop INHERITED-but-
        uninjected supervisor vars (a user's LD_LIBRARY_PATH-style site
        var). It doesn't, because the assignment spec carries the same
        full_env snapshot the cold path passes to Popen — pinned here by
        running the same module both ways under a sentinel inherited var
        and comparing the complete environment fingerprints."""
        import json

        monkeypatch.setenv("TPUJOB_FAKE_SITE", "inherited-not-injected")
        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            standby_pid = next(iter(runner._standby_pool._procs.values())).pid

            def run_and_fingerprint(index):
                h = runner.create(
                    KEY, ReplicaType.MASTER, index,
                    probe_template(PROBE_DUMP_ENV="1"), {},
                )
                assert wait_for(
                    lambda: (runner.sync(), runner.get(h.name).is_finished())[1]
                )
                assert runner.get(h.name).exit_code == 0
                text = open(runner.get(h.name).log_path).read()
                line = next(
                    ln for ln in text.splitlines()
                    if ln.startswith("probe-environ ")
                )
                return h, json.loads(line[len("probe-environ "):])

            h_warm, env_warm = run_and_fingerprint(0)
            assert h_warm.pid == standby_pid, "first run did not go warm"
            # Drain the pool so the second run is a cold spawn.
            runner._standby_pool.set_size(0)
            taken = runner._standby_pool.take()
            if taken is not None:
                runner._standby_pool.kill(*taken)
            h_cold, env_cold = run_and_fingerprint(1)
            assert h_cold.pid != standby_pid

            assert env_warm.get("TPUJOB_FAKE_SITE") == "inherited-not-injected"
            assert env_warm == env_cold, {
                "warm_only": {
                    k: v for k, v in env_warm.items()
                    if env_cold.get(k) != v
                },
                "cold_only": {
                    k: v for k, v in env_cold.items()
                    if env_warm.get(k) != v
                },
            }
        finally:
            runner.shutdown()

    def test_take_resets_crash_backoff(self, tmp_path):
        """ADVICE r3: a standby that reaches READY and is claimed between
        replenish passes must reset the crash-loop backoff — otherwise a
        drained pool carries a stale streak and one later pre-READY death
        jumps straight to the capped 60s delay."""
        pool = StandbyPool(tmp_path, size=1)
        try:
            pool._fail_streak = 6  # as if spawns had been crash-looping
            pool._not_before = 0.0
            assert wait_for(
                lambda: (pool.replenish(), pool.ready_count() == 1)[1]
            )
            taken = pool.take()
            assert taken is not None
            pool.kill(*taken)
            assert pool._fail_streak == 0, (
                "READY observed via take() did not reset the backoff streak"
            )
        finally:
            pool.shutdown()

    def test_cold_fallback_when_no_standby_ready(self, tmp_path):
        """Pool exhausted (or still importing): create() must not block
        on warmth — it cold-spawns."""
        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            sid, proc = runner._standby_pool.take()  # drain the pool
            runner._standby_pool.kill(sid, proc)
            h = runner.create(
                KEY, ReplicaType.MASTER, 0,
                probe_template(PROBE_VAL="cold"), {},
            )
            assert wait_for(
                lambda: (runner.sync(), runner.get(h.name).is_finished())[1]
            )
            assert runner.get(h.name).exit_code == 0
        finally:
            runner.shutdown()

    def test_command_templates_spawn_cold(self, tmp_path):
        """Only module templates are standby-eligible (exec'ing an argv
        would discard the warm imports)."""
        import sys

        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            standby_pid = next(iter(runner._standby_pool._procs.values())).pid
            h = runner.create(
                KEY, ReplicaType.MASTER, 0,
                ProcessTemplate(command=[sys.executable, "-c", "print('cmd')"]),
                {},
            )
            assert h.pid != standby_pid
            assert runner._standby_pool.ready_count() == 1  # untouched
            assert wait_for(
                lambda: (runner.sync(), runner.get(h.name).is_finished())[1]
            )
        finally:
            runner.shutdown()

    def test_signal_death_with_surviving_child_is_a_death(self, tmp_path):
        """A standby-run replica has no sh wrapper: its pid IS the
        workload, so a signal killing that pid is a replica death even
        when a same-group descendant (data-loader worker) survives. The
        cold path's wrapper-survivor demotion must NOT apply — the job
        would otherwise hang un-restarted until the stray child exits."""
        import signal

        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            h = runner.create(
                KEY, ReplicaType.MASTER, 0,
                probe_template(PROBE_SLEEP="120", PROBE_SPAWN_CHILD="120"), {},
            )
            assert wait_for(
                lambda: any(
                    "probe-env" in p.read_text()
                    for p in (tmp_path / "logs").glob("*warm-master-0.log")
                )
            )
            time.sleep(0.3)  # let the sleep child spawn
            os.kill(h.pid, signal.SIGKILL)  # the MAIN pid only, not the group
            assert wait_for(
                lambda: (runner.sync(), runner.get(h.name).is_finished())[1],
                15,
            ), "signal death masked by the surviving group child"
            got = runner.get(h.name)
            assert got.phase == ReplicaPhase.FAILED
            assert got.exit_code == 137  # signal death, retryable
        finally:
            runner.shutdown()

    def test_orphaned_standby_exits_when_pool_dir_removed(self, tmp_path):
        """A supervisor that dies without shutdown() must not leak
        standbys: the poll loop exits when the pool dir disappears."""
        import shutil

        pool = StandbyPool(tmp_path, size=1)
        pool.replenish()
        assert wait_for(lambda: pool.ready_count() == 1)
        (sid, proc), = list(pool._procs.items())
        shutil.rmtree(pool.dir)
        assert wait_for(lambda: proc.poll() is not None, 15), (
            "standby kept polling after its pool dir vanished"
        )

    def test_delete_kills_standby_run_replica(self, tmp_path):
        """A standby-run replica is a normal replica for teardown: its
        pid/pgid IS the workload's."""
        runner = SubprocessRunner(tmp_path, standby=1)
        try:
            assert wait_for(lambda: runner._standby_pool.ready_count() == 1)
            h = runner.create(
                KEY, ReplicaType.MASTER, 0,
                probe_template(PROBE_SLEEP="120"), {},
            )
            name = replica_name(KEY, ReplicaType.MASTER, 0)
            # Wait until the standby claimed + started the probe.
            assert wait_for(
                lambda: any(
                    "probe-env" in p.read_text()
                    for p in (tmp_path / "logs").glob("*warm-master-0.log")
                )
            )
            runner.delete(name, grace_seconds=1.0)
            assert wait_for(lambda: pid_gone(h.pid), 15)
        finally:
            runner.shutdown()


class TestSupervisorStandby:
    def test_job_completes_and_idle_standbys_die_on_shutdown(self, tmp_path):
        sup = Supervisor(
            state_dir=tmp_path / "state", poll_interval=0.05, standby=2
        )
        pool = sup.runner._standby_pool
        try:
            assert wait_for(lambda: pool.ready_count() >= 1)
            job = new_job(name="warmjob", workers=0, module="tests.standby_probe")
            done = sup.run(job, timeout=120)
            assert done.is_succeeded(), [
                c.to_dict() for c in done.status.conditions
            ]
        finally:
            idle_pids = [p.pid for p in pool._procs.values()]
            sup.shutdown()
        assert all(wait_for(lambda: pid_gone(pid), 10) for pid in idle_pids)
