"""The continuous-batching serving stack (serving/engine.py + spool.py
+ workloads/serve.py).

The load-bearing property: a mixed-length request stream served through
shared cache slots produces EXACTLY the tokens each request would get
generated alone (greedy parity vs make_generate), while slots recycle
and latency accounting (TTFT, per-token samples) accrues.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import llama as llama_lib
from pytorch_operator_tpu.serving import Request, ServingEngine, Spool


def _cfg_params(max_decode_len=48, **over):
    import jax
    import flax.linen as nn

    cfg = llama_lib.llama_tiny(decode=True, max_decode_len=max_decode_len, **over)
    params = nn.meta.unbox(
        llama_lib.Llama(dataclasses.replace(cfg, decode=False)).init(
            jax.random.key(0), np.zeros((1, 8), np.int32)
        )["params"]
    )
    return cfg, params


def _reference_rollout(cfg, params, prompt, new):
    """make_generate (B=1, uniform single-stream path) — the parity
    oracle for every engine rollout."""
    import jax
    import jax.numpy as jnp

    from pytorch_operator_tpu.workloads.generate import (
        init_cache,
        make_generate,
    )

    model = llama_lib.Llama(cfg)
    gen = make_generate(model, max_new_tokens=new)
    cache = init_cache(model, 1, len(prompt))
    toks, _ = gen(
        params, cache, jnp.asarray(prompt[None, :]), jax.random.key(0)
    )
    return [int(t) for t in np.asarray(toks)[0]]


def _req(rid, prompt, new):
    return Request(
        id=rid, prompt=prompt, max_new_tokens=new, submit_time=time.time()
    )


@pytest.mark.slow
class TestEngineParity:
    def test_mixed_lengths_match_single_stream(self):
        """Mixed prompt lengths and budgets through 3 shared slots: every
        request token-for-token equal to its single-stream rollout."""
        cfg, params = _cfg_params()
        eng = ServingEngine(cfg, params, slots=3, chunk=8, block=4)
        rng = np.random.default_rng(0)
        shapes = [(5, 7), (13, 9), (8, 3), (21, 5)]
        reqs = [
            _req(f"r{i}", rng.integers(0, 256, (p,)).astype(np.int32), n)
            for i, (p, n) in enumerate(shapes)
        ]
        for r in reqs:
            eng.submit(r)
        results = {r.id: r for r in eng.run_until_drained()}
        assert sorted(results) == [f"r{i}" for i in range(len(shapes))]
        for r in reqs:
            want = _reference_rollout(cfg, params, r.prompt, r.max_new_tokens)
            assert results[r.id].tokens == want, r.id

    def test_slot_reuse_preserves_parity(self):
        """More requests than slots: later requests land in RECYCLED
        slots whose caches hold a finished stream's leftovers — the
        write-before-read masking must keep them exact."""
        cfg, params = _cfg_params()
        eng = ServingEngine(cfg, params, slots=2, chunk=8, block=4)
        rng = np.random.default_rng(1)
        reqs = [
            _req(f"q{i}", rng.integers(0, 256, (p,)).astype(np.int32), n)
            for i, (p, n) in enumerate(
                [(6, 8), (11, 4), (4, 10), (17, 6), (9, 9)]
            )
        ]
        for r in reqs:
            eng.submit(r)
        results = {r.id: r for r in eng.run_until_drained()}
        assert len(results) == 5
        for r in reqs:
            want = _reference_rollout(cfg, params, r.prompt, r.max_new_tokens)
            assert results[r.id].tokens == want, r.id
        # All 5 went through 2 slots — reuse actually happened.
        assert eng.slots == 2

    def test_int8_stack_composes(self):
        """The serving stack's production config: int8 weights + int8
        KV through the engine, parity vs the single-stream rollout on
        the SAME quantized params."""
        import jax

        from pytorch_operator_tpu.ops.quantize import quantize_tree

        cfg, params = _cfg_params(kv_quantize="int8")
        cfg = dataclasses.replace(cfg, quantize="int8")
        qparams = jax.jit(quantize_tree)(params)
        eng = ServingEngine(cfg, qparams, slots=2, chunk=8, block=4)
        rng = np.random.default_rng(2)
        reqs = [
            _req(f"s{i}", rng.integers(0, 256, (p,)).astype(np.int32), n)
            for i, (p, n) in enumerate([(7, 6), (12, 8), (5, 4)])
        ]
        for r in reqs:
            eng.submit(r)
        results = {r.id: r for r in eng.run_until_drained()}
        for r in reqs:
            want = _reference_rollout(cfg, qparams, r.prompt, r.max_new_tokens)
            assert results[r.id].tokens == want, r.id

    def test_eos_frees_slot_early(self):
        """A request hitting EOS finishes before its budget and frees
        the slot; the emitted tokens stop at (and include) EOS."""
        cfg, params = _cfg_params()
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, 256, (6,)).astype(np.int32)
        # Find the greedy rollout, then declare its 3rd token EOS.
        full = _reference_rollout(cfg, params, prompt, 12)
        eos = full[2]
        eng = ServingEngine(
            cfg, params, slots=1, chunk=8, block=4, eos_token=eos
        )
        eng.submit(_req("e0", prompt, 12))
        (res,) = eng.run_until_drained()
        assert res.tokens == full[:3]
        assert res.tokens[-1] == eos

    def test_temperature_sampling_serves(self):
        """T>0 exercises the one-dispatch first-token sampler and the
        device sampler in the decode blocks; tokens must be in-range
        and the full budget delivered."""
        cfg, params = _cfg_params()
        eng = ServingEngine(
            cfg, params, slots=2, chunk=8, block=4,
            temperature=1.0, top_k=8, seed=3,
        )
        rng = np.random.default_rng(5)
        for i in range(2):
            eng.submit(
                _req(f"t{i}", rng.integers(0, 256, (6,)).astype(np.int32), 5)
            )
        results = eng.run_until_drained()
        assert len(results) == 2
        for r in results:
            assert len(r.tokens) == 5
            assert all(0 <= t < cfg.vocab_size for t in r.tokens)

    def test_latency_accounting(self):
        cfg, params = _cfg_params()
        eng = ServingEngine(cfg, params, slots=2, chunk=8, block=4)
        rng = np.random.default_rng(4)
        for i in range(3):
            eng.submit(
                _req(f"m{i}", rng.integers(0, 256, (6,)).astype(np.int32), 6)
            )
        results = eng.run_until_drained()
        s = eng.stats()
        assert s["requests"] == 3 and s["generated_tokens"] == 18
        assert s["decode_tokens_per_sec"] > 0
        for k in ("ttft_ms_p50", "ttft_ms_p99", "tpot_ms_p50", "tpot_ms_p99"):
            assert s[k] is not None and s[k] > 0, k
        for r in results:
            assert r.ttft_s >= r.admit_wait_s >= 0
            assert r.tpot_s is None or r.tpot_s > 0


class TestEngineValidation:
    def test_budget_rejected_at_submit(self):
        cfg, params = _cfg_params(max_decode_len=32)
        eng = ServingEngine(cfg, params, slots=1, chunk=8, block=2)
        with pytest.raises(ValueError, match="cache budget"):
            eng.submit(
                _req("big", np.zeros((20,), np.int32), 12)  # 20+12 > 31
            )
        with pytest.raises(ValueError, match="empty"):
            eng.submit(_req("empty", np.zeros((0,), np.int32), 4))
        # A zero/negative budget would still emit the prefill's first
        # token (and weaken the cache-budget inequality).
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(_req("zero", np.zeros((4,), np.int32), 0))
        with pytest.raises(ValueError, match="max_new_tokens"):
            eng.submit(_req("neg", np.zeros((4,), np.int32), -5))

    def test_needs_decode_config(self):
        cfg, params = _cfg_params()
        with pytest.raises(ValueError, match="decode"):
            ServingEngine(
                dataclasses.replace(cfg, decode=False), params, slots=1
            )


class TestSpool:
    def test_submit_claim_respond_roundtrip(self, tmp_path):
        sp = Spool(tmp_path / "sp")
        a = sp.submit(prompt=[1, 2, 3], max_new_tokens=4)
        b = sp.submit(prompt_len=7, max_new_tokens=2)
        assert sp.pending_count() == 2
        recs = sp.claim(10)
        assert [r["id"] for r in recs] == [a, b]  # oldest first
        assert recs[0]["prompt"] == [1, 2, 3]
        assert recs[1]["prompt_len"] == 7
        assert sp.pending_count() == 0
        sp.respond(a, {"tokens": [9, 9]})
        assert sp.wait_response(a, timeout=5)["tokens"] == [9, 9]
        with pytest.raises(TimeoutError):
            sp.wait_response(b, timeout=0.1)

    def test_tmp_files_invisible_to_claim(self, tmp_path):
        sp = Spool(tmp_path / "sp")
        (sp.requests / ".partial.tmp").write_text("{not json")
        assert sp.claim(5) == []
        assert sp.pending_count() == 0

    def test_claim_limit(self, tmp_path):
        sp = Spool(tmp_path / "sp")
        for _ in range(4):
            sp.submit(prompt_len=3, max_new_tokens=1)
        assert len(sp.claim(2)) == 2
        assert sp.pending_count() == 2

    def test_recover_claimed_requeues_orphans(self, tmp_path):
        """A crashed engine's in-flight claims must become requests
        again on restart (the supervisor restart policy re-runs the
        job; orphaned clients would otherwise wait out their
        timeouts). Already-answered claims are NOT re-run."""
        sp = Spool(tmp_path / "sp")
        a = sp.submit(prompt_len=3, max_new_tokens=2)
        b = sp.submit(prompt_len=4, max_new_tokens=2)
        sp.claim(2)  # both in flight
        assert sp.pending_count() == 0
        # Simulate a crash AFTER b's response was written but before
        # its claim was unlinked.
        (sp.responses / f"{b}.json").write_text('{"tokens": []}')
        assert sp.recover_claimed() == 1
        assert sp.pending_count() == 1
        assert sp.recover_claimed() == 0  # nothing left to recover
        assert [r["id"] for r in sp.claim(5)] == [a]

    def test_submit_validates(self, tmp_path):
        sp = Spool(tmp_path / "sp")
        with pytest.raises(ValueError, match="exactly one"):
            sp.submit(prompt=[1], prompt_len=3)
        with pytest.raises(ValueError, match="exactly one"):
            sp.submit()


@pytest.mark.slow
def test_serve_job_under_supervisor(tmp_path):
    """The operator-analog serving journey end to end: a REAL serve job
    under the supervisor (subprocess, rendezvous env, progress surface),
    fed by a client through the spool, exiting cleanly after its request
    budget — the reconciled-workload lifecycle applied to inference."""
    import threading

    from pytorch_operator_tpu.api import (
        ProcessTemplate,
        ReplicaType,
        Resources,
    )
    from pytorch_operator_tpu.controller import Supervisor
    from tests.testutil import new_job

    spool_dir = tmp_path / "spool"
    sp = Spool(spool_dir)
    got = {}

    def client():
        ids = [
            sp.submit(prompt_len=5, max_new_tokens=6),
            sp.submit(prompt=[3, 1, 4, 1, 5], max_new_tokens=4),
        ]
        for rid in ids:
            got[rid] = sp.wait_response(rid, timeout=240)

    t = threading.Thread(target=client)
    t.start()
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.1)
    job = new_job(name="serve-e2e", workers=0)
    job.spec.port = None
    job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
        module="pytorch_operator_tpu.workloads.serve",
        args=[
            "--config", "tiny", "--spool", str(spool_dir),
            "--slots", "2", "--chunk", "8", "--block", "4",
            "--max-decode-len", "48", "--max-requests", "2",
            "--idle-timeout", "120", "--json",
        ],
        resources=Resources(cpu_devices=1),
    )
    done = sup.run(job, timeout=240)
    t.join(timeout=60)
    log = (
        tmp_path / "state" / "logs" / "default_serve-e2e-master-0.log"
    ).read_text()
    assert done.is_succeeded(), f"log:\n{log[-3000:]}"
    assert not t.is_alive()
    assert len(got) == 2
    for r in got.values():
        assert len(r["tokens"]) in (4, 6)
        assert r["ttft_ms"] > 0
    # The serving job reports through the same progress surface as
    # training jobs: the status stream carries a metrics record with
    # the latency percentiles.
    import json as _json

    from pytorch_operator_tpu.controller.progress import job_status_dir
    from pytorch_operator_tpu.controller.store import job_key

    status = (
        job_status_dir(tmp_path / "state" / "status", job_key(done))
        / "master-0.jsonl"
    ).read_text()
    metrics = [
        r for r in map(_json.loads, status.splitlines())
        if r.get("event") == "metrics" and "ttft_ms_p50" in r
    ]
    assert metrics and metrics[-1]["requests"] == 2, status[-1500:]
    sup.shutdown()


@pytest.mark.slow
class TestServeWorkload:
    def test_serve_loop_with_concurrent_client(self, tmp_path):
        """The workload surface: serve.run() against a spool a client
        thread feeds while the loop runs — mixed lengths, responses
        with latency fields, a bad request rejected with an error."""
        import threading

        from pytorch_operator_tpu.workloads import serve as serve_mod

        spool_dir = tmp_path / "spool"
        sp = Spool(spool_dir)
        ids = [sp.submit(prompt_len=5, max_new_tokens=6)]
        got = {}

        def client():
            time.sleep(3)
            ids.append(sp.submit(prompt=[1, 2, 3, 4], max_new_tokens=4))
            ids.append(
                sp.submit(prompt_len=30, max_new_tokens=40)
            )  # over budget at L=48 -> rejected
            for rid in list(ids):
                got[rid] = sp.wait_response(rid, timeout=240)

        t = threading.Thread(target=client)
        t.start()
        stats = serve_mod.run(
            config="tiny", spool_dir=str(spool_dir), slots=2, chunk=8,
            block=4, max_decode_len=48, max_requests=2, idle_timeout=60,
            log=lambda *_: None,
        )
        t.join(timeout=300)
        assert not t.is_alive()
        assert stats["served"] == 2 and stats["rejected"] == 1
        ok = [r for r in got.values() if "tokens" in r]
        bad = [r for r in got.values() if "error" in r]
        assert len(ok) == 2 and len(bad) == 1
        for r in ok:
            assert len(r["tokens"]) in (4, 6)
            assert r["ttft_ms"] > 0
        assert "budget" in bad[0]["error"]
        assert stats["ttft_ms_p50"] > 0 and stats["tpot_ms_p50"] > 0


class TestServeRequestCLI:
    """`tpujob serve-request` — the client half of the serving service
    as a first-class CLI surface (no server needed for these: the spool
    IS the contract)."""

    def _cli(self, *argv):
        from pytorch_operator_tpu.client.cli import main

        return main(list(argv))

    def test_no_wait_submits_a_claimable_request(self, tmp_path, capsys):
        spool = tmp_path / "sp"
        Spool(spool)  # the serve job owns spool creation
        rc = self._cli(
            "serve-request", "--spool", str(spool),
            "--prompt", "3,1,4,1,5", "--max-new-tokens", "7", "--no-wait",
        )
        assert rc == 0
        rid = capsys.readouterr().out.strip()
        (rec,) = Spool(spool).claim(5)
        assert rec["id"] == rid
        assert rec["prompt"] == [3, 1, 4, 1, 5]
        assert rec["max_new_tokens"] == 7

    def test_wait_returns_the_engine_response(self, tmp_path, capsys):
        import json
        import threading

        spool_dir = tmp_path / "sp"
        sp = Spool(spool_dir)

        def fake_engine():
            # Answer the first request that shows up.
            import time as _t

            deadline = _t.time() + 30
            while _t.time() < deadline:
                recs = sp.claim(1)
                if recs:
                    sp.respond(
                        recs[0]["id"],
                        {"tokens": [9, 8], "ttft_ms": 12.0, "tpot_ms": 3.0},
                    )
                    return
                _t.sleep(0.02)

        t = threading.Thread(target=fake_engine)
        t.start()
        rc = self._cli(
            "serve-request", "--spool", str(spool_dir),
            "--prompt-len", "5", "--max-new-tokens", "2", "--timeout", "30",
        )
        t.join()
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert out["tokens"] == [9, 8] and out["ttft_ms"] == 12.0

    def test_bad_args_and_timeout(self, tmp_path, capsys):
        spool = str(tmp_path / "sp")
        assert self._cli("serve-request", "--spool", spool) == 2
        assert (
            self._cli(
                "serve-request", "--spool", spool,
                "--prompt", "1,2", "--prompt-len", "3",
            )
            == 2
        )
        assert (
            self._cli(
                "serve-request", "--spool", spool,
                "--prompt", "not,ints",
            )
            == 2
        )
        # A prompt with no valid ids is rejected locally, not after a
        # guaranteed-error server round trip.
        assert (
            self._cli(
                "serve-request", "--spool", spool, "--prompt", ","
            )
            == 2
        )
        # Arg errors must NOT have created the spool as a side effect,
        # and a missing spool is a clear client-side error (rc 1), not
        # a 300s hang against directories nothing reads.
        import pathlib

        assert not pathlib.Path(spool).exists()
        assert (
            self._cli(
                "serve-request", "--spool", spool,
                "--prompt-len", "4", "--timeout", "0.2",
            )
            == 1
        )
        assert "does not exist" in capsys.readouterr().err
        # With a live spool but nothing serving: the wait times out, rc 1.
        Spool(spool)
        assert (
            self._cli(
                "serve-request", "--spool", spool,
                "--prompt-len", "4", "--timeout", "0.2",
            )
            == 1
        )


@pytest.mark.slow
def test_serve_loop_churn_under_threaded_clients(tmp_path, monkeypatch):
    """Stress the spool+engine+loop composition: 12 requests from 3
    client threads with jittered submit timing into 2 slots — every
    request answered exactly once, no response lost or duplicated
    (the serving analog of the control plane's test_stress.py)."""
    import collections
    import threading

    from pytorch_operator_tpu.workloads import serve as serve_mod

    spool_dir = tmp_path / "spool"
    sp = Spool(spool_dir)
    results = {}
    lock = threading.Lock()
    # Count engine-side respond() calls per id — the only place
    # duplication is actually observable (a double respond would
    # silently overwrite the same response file).
    respond_counts = collections.Counter()
    real_respond = Spool.respond

    def counting_respond(self, request_id, record):
        with lock:
            respond_counts[request_id] += 1
        return real_respond(self, request_id, record)

    monkeypatch.setattr(Spool, "respond", counting_respond)
    rng = np.random.default_rng(0)
    plans = [
        [(int(rng.integers(3, 20)), int(rng.integers(2, 10)))
         for _ in range(4)]
        for _ in range(3)
    ]

    def client(plan, jitter):
        for p, n in plan:
            time.sleep(jitter)
            rid = sp.submit(prompt_len=p, max_new_tokens=n)
            r = sp.wait_response(rid, timeout=240)
            with lock:
                results[rid] = (n, r)

    threads = [
        threading.Thread(target=client, args=(plan, 0.2 * i))
        for i, plan in enumerate(plans)
    ]
    for t in threads:
        t.start()
    stats = serve_mod.run(
        config="tiny", spool_dir=str(spool_dir), slots=2, chunk=8,
        block=4, max_decode_len=48, max_requests=12, idle_timeout=120,
        log=lambda *_: None,
    )
    for t in threads:
        t.join(timeout=120)
        assert not t.is_alive()
    assert stats["served"] == 12 and stats["rejected"] == 0
    assert len(results) == 12
    # Exactly-once: every submitted id answered by exactly ONE engine
    # respond() call.
    assert sorted(respond_counts) == sorted(results)
    assert set(respond_counts.values()) == {1}, respond_counts
    for rid, (n, r) in results.items():
        assert len(r["tokens"]) == n, rid
        assert r["ttft_ms"] > 0
    # The spool drained completely: nothing claimed or pending.
    assert sp.pending_count() == 0
    assert list(sp.claimed.iterdir()) == []
