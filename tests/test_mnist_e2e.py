"""The minimum end-to-end slice (SURVEY.md §7 build-order step 3): submit →
supervise → train real digits → accuracy gate → Succeeded, with
schedule-to-first-step latency recorded.
"""

import pytest

from pytorch_operator_tpu.api import ProcessTemplate, ReplicaType, Resources
from pytorch_operator_tpu.controller import Supervisor, schedule_to_first_step_latency
from tests.testutil import new_job


@pytest.mark.slow
def test_mnist_trains_end_to_end(tmp_path):
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.1)
    job = new_job(name="mnist-e2e", workers=0)
    job.spec.port = None  # auto-allocate
    job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
        module="pytorch_operator_tpu.workloads.mnist_train",
        args=["--epochs", "3", "--target-acc", "0.90"],
        resources=Resources(cpu_devices=1),
    )
    done = sup.run(job, timeout=240)
    log = (tmp_path / "state" / "logs" / "default_mnist-e2e-master-0.log").read_text()
    assert done.is_succeeded(), f"log:\n{log}"
    assert "test_accuracy=" in log
    lat = schedule_to_first_step_latency(done)
    assert lat is not None and lat > 0
    sup.shutdown()
