"""Ring attention (sequence/context parallelism) numerics.

Validates parallel/ring.py against the dense oracle on the 8-device CPU
mesh (SURVEY.md §4 "Rebuild translation": multi-device semantics proven on
the forced-device-count CPU backend).
"""

import tests.jaxenv  # noqa: F401  (forces the CPU backend first)

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_operator_tpu.parallel import make_mesh, ring_self_attention
from pytorch_operator_tpu.parallel.ring import _single_shard


def _qkv(B=2, S=32, K=2, G=2, D=8, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, S, K, G, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, S, K, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, S, K, D)), dtype)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    return q, k, v, pos


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense_oracle(causal):
    q, k, v, pos = _qkv()
    mesh = make_mesh("dp=2,sp=4")
    ref = _single_shard(q, k, v, pos, causal=causal)
    out = jax.jit(
        lambda q, k, v, p: ring_self_attention(q, k, v, p, mesh, causal=causal)
    )(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_falls_back_when_seq_does_not_divide_sp():
    """S % sp != 0 cannot shard — must take the single-shard path, not
    raise at trace time."""
    q, k, v, pos = _qkv(S=30)
    mesh = make_mesh("sp=4", devices=jax.devices()[:4])
    out = ring_self_attention(q, k, v, pos, mesh)
    ref = _single_shard(q, k, v, pos, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ring_backward_residuals_stay_linear():
    """The remat'd ring body must not save per-step [.., Sq, Skv] softmax
    intermediates: the compiled grad program's temp memory stays far
    below the O(Sq_local * S_total) stack the un-remat'd loop carried."""
    B, S, K, G, D = 1, 256, 1, 1, 8
    q, k, v, pos = _qkv(B=B, S=S, K=K, G=G, D=D)
    mesh = make_mesh("sp=8")

    def loss(q, k, v):
        return (
            ring_self_attention(q, k, v, pos, mesh).astype(jnp.float32) ** 2
        ).mean()

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))
    ma = g.lower(q, k, v).compile().memory_analysis()
    if ma is None:
        pytest.skip("backend exposes no compiled memory analysis")
    # Un-remat'd residual stack alone: n_steps * B*K*G*Sq*Skv f32
    # = 8 * 32 * 256 * 4 B = 256 KiB (plus everything else). Remat'd
    # temp measured well under that bound; assert the bound so a
    # regression (dropping jax.checkpoint) trips it.
    residual_stack_bytes = 8 * B * K * G * (S // 8) * S * 4
    assert ma.temp_size_in_bytes < residual_stack_bytes, (
        f"grad temp {ma.temp_size_in_bytes}B suggests per-step softmax "
        f"residuals are being saved again"
    )


def test_ring_degenerate_mesh_no_sp_axis():
    """Without an sp axis the wrapper must fall back to single-shard math."""
    q, k, v, pos = _qkv(S=16)
    mesh = make_mesh("dp=8")
    out = ring_self_attention(q, k, v, pos, mesh)
    ref = _single_shard(q, k, v, pos, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ring_gradients_match_dense():
    """d(out)/d(q,k,v) flows correctly through ppermute + fori_loop."""
    q, k, v, pos = _qkv(B=1, S=16, K=1, G=2, D=4)
    mesh = make_mesh("sp=4,tp=2")

    def loss_ring(q, k, v):
        return ring_self_attention(q, k, v, pos, mesh).sum()

    def loss_ref(q, k, v):
        return _single_shard(q, k, v, pos, causal=True).sum()

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_llama_ring_equals_dense_logits():
    """The full model produces the same logits under attn_impl='ring'."""
    from pytorch_operator_tpu.models.llama import Llama, llama_tiny

    mesh = make_mesh("fsdp=2,sp=2,tp=2")
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, size=(2, 16)), jnp.int32
    )
    dense = Llama(llama_tiny())
    variables = dense.init(jax.random.key(0), tokens)
    ref = dense.apply(variables, tokens)
    ring = Llama(llama_tiny(attn_impl="ring"), mesh=mesh)
    out = jax.jit(lambda v, t: ring.apply(v, t))(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)
