"""Control-plane bench smoke lane (``-m bench_smoke``, also tier-1).

Runs the real harness at N=10 with few passes — small enough for the
tier-1 time budget, real enough to catch hot-path regressions: a change
that reintroduces per-pass re-reads, per-pass rewrites of idle jobs, or
per-job directory globs shows up here as nonzero idle I/O, long before
anyone reruns the full N=1000 artifact.
"""

from __future__ import annotations

import json

import pytest

from pytorch_operator_tpu.workloads import ctrlplane_bench

pytestmark = pytest.mark.bench_smoke


@pytest.fixture(scope="module")
def smoke_result(tmp_path_factory):
    td = tmp_path_factory.mktemp("ctrlplane")
    return ctrlplane_bench.run(
        jobs=[10], passes=5, work_dir=str(td), log=lambda *_: None
    )


def cell(result, mode):
    return next(c for c in result["cells"] if c["mode"] == mode)


@pytest.fixture(scope="module")
def sharded_result(tmp_path_factory):
    """A real two-supervisor sharded cell at smoke scale: one shared
    state dir, per-shard leases, each supervisor running the full
    daemon loop body."""
    td = tmp_path_factory.mktemp("ctrlplane-sharded")
    return ctrlplane_bench.bench_sharded(
        40, 2, 6, td, lease_ttl=1.0, log=lambda *_: None
    )


@pytest.fixture(scope="module")
def churn_result(tmp_path_factory):
    td = tmp_path_factory.mktemp("ctrlplane-churn")
    return ctrlplane_bench.bench_sharded(
        24, 2, 4, td, replicas=3, churn_markers=8, lease_ttl=1.0,
        log=lambda *_: None,
    )


class TestShardedSmoke:
    def test_no_job_is_double_reconciled(self, sharded_result):
        # THE exactly-once pin: under a 2-supervisor split, no job ever
        # has live worlds in both runners.
        assert sharded_result["double_reconciles"] == 0

    def test_every_job_has_exactly_one_owner(self, sharded_result):
        assert sum(sharded_result["jobs_per_supervisor"]) == 40
        assert all(n > 0 for n in sharded_result["jobs_per_supervisor"])

    def test_idle_store_io_is_zero_per_shard_owner(self, sharded_result):
        # The zero-idle-I/O invariant survives the shard split: each
        # supervisor's idle pass reads/writes NO job files for its
        # shards (lease renewals live outside the store on purpose).
        assert sharded_result["idle_reads_per_pass_per_supervisor"] == [0, 0]
        assert sharded_result["idle_writes_per_pass_per_supervisor"] == [0, 0]

    def test_autoscaler_respects_its_bounds(self, sharded_result):
        # Pool never exceeds --sync-workers-max, and an idle fleet
        # shrinks it back to the floor.
        assert (
            sharded_result["sync_pool_max_seen"]
            <= sharded_result["sync_pool_ceiling"]
        )
        assert (
            sharded_result["sync_pool_final"]
            == sharded_result["sync_pool_floor"]
        )

    def test_drain_completes_across_supervisors(self, sharded_result):
        assert sharded_result["unfinished_after_drain"] == 0

    def test_shard_split_is_disjoint_and_complete(self, sharded_result):
        split = sharded_result["shard_split"]
        all_shards = [s for owned in split.values() for s in owned]
        assert sorted(all_shards) == list(range(sharded_result["shards"]))

    def test_churn_cell_stays_exactly_once_with_wide_gangs(self, churn_result):
        # Marker storms (rename-claimed across two supervisors) on
        # 3-replica gangs: still no double worlds, still drains clean.
        assert churn_result["double_reconciles"] == 0
        assert churn_result["unfinished_after_drain"] == 0
        assert churn_result["churn_passes"] > 0
        assert churn_result["churn_pass_ms_p50"] > 0


class TestBenchSmoke:
    def test_cached_idle_pass_does_zero_job_file_io(self, smoke_result):
        cached = cell(smoke_result, "cached")
        # THE hot-path guard: an idle pass over a cached store must not
        # read or write a single job file. Any regression that puts
        # file I/O back on the steady-state path trips this.
        assert cached["idle_reads_per_pass"] == 0
        assert cached["idle_writes_per_pass"] == 0
        # O(1) clean check (TPUJob generation counter): the idle pass
        # does not even SERIALIZE a job to discover it is clean.
        assert cached["idle_serializations_per_pass"] == 0
        # One scandir snapshot serves rescan + all marker scans.
        assert cached["idle_scans_per_pass"] <= 1.0

    def test_watch_engine_is_free_on_idle_fleets(self, smoke_result):
        # The live health engine (obs/watch.py) rides the same pass:
        # jobs that never reported must cost it NOTHING — no alert-log
        # appends, and not even a rule evaluation (untracked jobs skip
        # the evaluator entirely). Both modes, since the watch runs
        # regardless of the store flavor.
        for mode in ("cached", "legacy"):
            c = cell(smoke_result, mode)
            assert c["idle_watch_log_appends"] == 0
            assert c["idle_watch_evaluations"] == 0

    def test_remediation_engine_is_free_on_healthy_fleets(self, smoke_result):
        # Every bench job carries an ARMED remediation policy, nothing
        # ever fires: across the idle passes the engine must write no
        # audit records and take no actions — the closed loop costs
        # zero I/O until an alert actually asks for an action.
        for mode in ("cached", "legacy"):
            c = cell(smoke_result, mode)
            assert c["idle_remediation_log_appends"] == 0
            assert c["idle_remediation_actions"] == 0

    def test_legacy_mode_still_measures_the_old_profile(self, smoke_result):
        legacy = cell(smoke_result, "legacy")
        # The baseline must stay honest: N reads and N writes per idle
        # pass (one per job), plus the per-kind marker globs — otherwise
        # the artifact's comparison silently measures nothing.
        assert legacy["idle_reads_per_pass"] == 10
        assert legacy["idle_writes_per_pass"] == 10
        assert legacy["idle_scans_per_pass"] >= 5

    def test_churn_completes_all_jobs_in_both_modes(self, smoke_result):
        for mode in ("cached", "legacy"):
            assert cell(smoke_result, mode)["unfinished_after_drain"] == 0

    def test_artifact_shape_is_committed_schema(self, smoke_result, tmp_path):
        out = tmp_path / "bench.json"
        ctrlplane_bench.run(
            jobs=[10], passes=2, out=str(out),
            work_dir=str(tmp_path), log=lambda *_: None,
        )
        data = json.loads(out.read_text())
        assert data["bench"] == "control_plane"
        assert data["comparisons"][0]["jobs"] == 10
        for field in (
            "pass_p50_speedup",
            "pass_p99_speedup",
            "idle_read_reduction",
            "idle_write_reduction",
        ):
            assert field in data["comparisons"][0]
