"""Control-plane bench smoke lane (``-m bench_smoke``, also tier-1).

Runs the real harness at N=10 with few passes — small enough for the
tier-1 time budget, real enough to catch hot-path regressions: a change
that reintroduces per-pass re-reads, per-pass rewrites of idle jobs, or
per-job directory globs shows up here as nonzero idle I/O, long before
anyone reruns the full N=1000 artifact.
"""

from __future__ import annotations

import json

import pytest

from pytorch_operator_tpu.workloads import ctrlplane_bench

pytestmark = pytest.mark.bench_smoke


@pytest.fixture(scope="module")
def smoke_result(tmp_path_factory):
    td = tmp_path_factory.mktemp("ctrlplane")
    return ctrlplane_bench.run(
        jobs=[10], passes=5, work_dir=str(td), log=lambda *_: None
    )


def cell(result, mode):
    return next(c for c in result["cells"] if c["mode"] == mode)


class TestBenchSmoke:
    def test_cached_idle_pass_does_zero_job_file_io(self, smoke_result):
        cached = cell(smoke_result, "cached")
        # THE hot-path guard: an idle pass over a cached store must not
        # read or write a single job file. Any regression that puts
        # file I/O back on the steady-state path trips this.
        assert cached["idle_reads_per_pass"] == 0
        assert cached["idle_writes_per_pass"] == 0
        # O(1) clean check (TPUJob generation counter): the idle pass
        # does not even SERIALIZE a job to discover it is clean.
        assert cached["idle_serializations_per_pass"] == 0
        # One scandir snapshot serves rescan + all marker scans.
        assert cached["idle_scans_per_pass"] <= 1.0

    def test_watch_engine_is_free_on_idle_fleets(self, smoke_result):
        # The live health engine (obs/watch.py) rides the same pass:
        # jobs that never reported must cost it NOTHING — no alert-log
        # appends, and not even a rule evaluation (untracked jobs skip
        # the evaluator entirely). Both modes, since the watch runs
        # regardless of the store flavor.
        for mode in ("cached", "legacy"):
            c = cell(smoke_result, mode)
            assert c["idle_watch_log_appends"] == 0
            assert c["idle_watch_evaluations"] == 0

    def test_legacy_mode_still_measures_the_old_profile(self, smoke_result):
        legacy = cell(smoke_result, "legacy")
        # The baseline must stay honest: N reads and N writes per idle
        # pass (one per job), plus the per-kind marker globs — otherwise
        # the artifact's comparison silently measures nothing.
        assert legacy["idle_reads_per_pass"] == 10
        assert legacy["idle_writes_per_pass"] == 10
        assert legacy["idle_scans_per_pass"] >= 5

    def test_churn_completes_all_jobs_in_both_modes(self, smoke_result):
        for mode in ("cached", "legacy"):
            assert cell(smoke_result, mode)["unfinished_after_drain"] == 0

    def test_artifact_shape_is_committed_schema(self, smoke_result, tmp_path):
        out = tmp_path / "bench.json"
        ctrlplane_bench.run(
            jobs=[10], passes=2, out=str(out),
            work_dir=str(tmp_path), log=lambda *_: None,
        )
        data = json.loads(out.read_text())
        assert data["bench"] == "control_plane"
        assert data["comparisons"][0]["jobs"] == 10
        for field in (
            "pass_p50_speedup",
            "pass_p99_speedup",
            "idle_read_reduction",
            "idle_write_reduction",
        ):
            assert field in data["comparisons"][0]
