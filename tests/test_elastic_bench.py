"""Elastic bench smoke lane (``-m bench_smoke``, also tier-1).

Runs the real resize-vs-restart harness at the smallest meaningful
scale — one 2-worker gang, both modes — and pins the elastic
tentpole's quantitative claims:

- resize-in-place recovery is STRICTLY faster than a whole-world
  restart for the same death (the whole point of shrinking instead of
  respawning);
- the post-resize rank assignment the survivors themselves report is
  unique and dense in [0, world) — no duplicate ranks, no holes;
- a shrink never cold-starts anyone (zero post-kill ``first_step``
  incarnations in the resize cell), while the restart cell respawns
  the entire gang.

The full {2,4,8}-gang artifact is BENCH_elastic.json; this lane keeps
the 2-worker cells honest inside the tier-1 budget.
"""

from __future__ import annotations

import pytest

from pytorch_operator_tpu.workloads import elastic_bench

pytestmark = pytest.mark.bench_smoke


@pytest.fixture(scope="module")
def cells():
    out = {}
    for mode in ("resize", "restart"):
        out[mode] = elastic_bench.run_cell(
            2, mode, pre_steps=3, step_time=0.02, timeout=90.0
        )
    return out


class TestElasticBenchSmoke:
    def test_resize_strictly_faster_than_restart(self, cells):
        assert (
            cells["resize"]["recovery_s"] < cells["restart"]["recovery_s"]
        ), cells

    def test_resize_ranks_unique_and_dense(self, cells):
        assert cells["resize"]["ranks_unique_dense"] is True, cells["resize"]
        assert cells["resize"]["ranks"] == [0, 1]

    def test_shrink_never_respawns(self, cells):
        # The survivors adopt in place; nobody cold-starts.
        assert cells["resize"]["post_kill_cold_starts"] == 0, cells["resize"]

    def test_restart_respawns_the_whole_gang(self, cells):
        # Master + 2 workers all come back as fresh incarnations.
        assert cells["restart"]["post_kill_cold_starts"] == 3, cells["restart"]

    def test_neither_mode_loses_committed_steps(self, cells):
        # exit_with checkpoints every step, so both recovery paths must
        # resume at-or-past the pre-death frontier (step_loss == 0).
        for mode in ("resize", "restart"):
            assert cells[mode]["step_loss"] == 0, cells[mode]
