"""CLI tests — the kubectl-surface analog, driven in-process via main()."""

import pytest

from pytorch_operator_tpu.client.cli import main


@pytest.fixture
def job_yaml(tmp_path):
    p = tmp_path / "job.yaml"
    p.write_text(
        """
metadata: {name: cli-job}
spec:
  replica_specs:
    Master:
      template: {module: pytorch_operator_tpu.workloads.noop}
    Worker:
      replicas: 1
      template: {module: pytorch_operator_tpu.workloads.noop}
"""
    )
    return p


def run_cli(*argv) -> int:
    return main([str(a) for a in argv])


def _flip_then_interrupt(state, mutate, delay=1.2):
    """Mutate the persisted cli-job from a daemon thread, then interrupt
    the main thread (the user's Ctrl-C on a watch). The interrupt fires
    even if the mutation fails — otherwise a broken flip would hang the
    watch loop (and the suite) forever."""
    import _thread
    import threading
    import time as _time

    from pytorch_operator_tpu.controller.store import JobStore

    def run():
        try:
            _time.sleep(delay)
            store = JobStore(persist_dir=state / "jobs")
            job = store.reload("default/cli-job")
            mutate(job)
            job.touch()  # mutate-then-touch: the store's dirty contract
            store.update(job)
        finally:
            _time.sleep(delay)
            _thread.interrupt_main()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    return t


class TestCLI:
    def test_get_describe_json_output(self, tmp_path, job_yaml, capsys):
        """kubectl -o json analog: parseable full objects round-trip."""
        import json as _json

        from pytorch_operator_tpu.api.types import TPUJob

        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()

        assert run_cli("--state-dir", state, "get", "--json") == 0
        listed = _json.loads(capsys.readouterr().out)
        assert isinstance(listed, list) and len(listed) == 1

        assert run_cli("--state-dir", state, "describe", "cli-job", "--json") == 0
        obj = _json.loads(capsys.readouterr().out)
        job = TPUJob.from_dict(obj)  # parseable AND loadable
        assert job.metadata.name == "cli-job"
        assert job.is_succeeded()

    def test_get_watch_streams_state_changes(self, tmp_path, job_yaml, capsys):
        """kubectl get -w analog: the watch loop re-prints the table when
        a job's state changes and exits on interrupt."""
        import threading
        import time as _time

        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()

        import pytorch_operator_tpu.client.cli as cli

        def bump(job):
            job.status.restart_count = 7

        t = _flip_then_interrupt(state, bump)
        rc = cli.main(["--state-dir", str(state), "get", "--watch"])
        t.join(5)
        out = capsys.readouterr().out
        assert rc == 0
        # State-fingerprint change detection: EXACTLY two renders (the
        # AGE column ticking must not cause re-renders — the watch ran
        # ~2.4s, so age churn would have produced more).
        headers = [l for l in out.splitlines() if l.startswith("NAME")]
        assert len(headers) == 2, out
        # The flipped restart count reached the stream, read from the
        # RESTARTS column of the final table (not a substring match an
        # age like '7s' could satisfy).
        final = out.split("---")[-1].strip().splitlines()
        header, row = final[0].split(), final[1].split()
        assert row[header.index("RESTARTS")] == "7", out

    def test_get_watch_json_streams_bare_snapshots(self, tmp_path, job_yaml, capsys):
        """kubectl -w -o json analog: no '---' separators in the JSON
        stream, and each snapshot is parseable."""
        import json as _json
        import threading
        import time as _time

        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()

        import pytorch_operator_tpu.client.cli as cli

        def bump(job):
            job.status.restart_count = 5

        t = _flip_then_interrupt(state, bump)
        rc = cli.main(["--state-dir", str(state), "get", "--watch", "--json"])
        t.join(5)
        out = capsys.readouterr().out
        assert rc == 0
        assert "---" not in out
        # First snapshot parses on its own (stream of bare arrays).
        first = out[: out.index("\n]") + 2]
        jobs = _json.loads(first)
        assert jobs[0]["metadata"]["name"] == "cli-job"
        # The flipped state reached the stream.
        assert '"restart_count": 5' in out

    def test_manifests_subcommand_checks_and_generates(self, tmp_path, capsys):
        assert run_cli("manifests", "--out-dir", tmp_path / "m") == 0
        capsys.readouterr()
        assert run_cli("manifests", "--out-dir", tmp_path / "m", "--check") == 0
        assert "up to date" in capsys.readouterr().out
        # ...and the stale path actually fires (non-tautological check).
        (tmp_path / "m" / "base" / "crd.yaml").write_text("tampered")
        assert run_cli("manifests", "--out-dir", tmp_path / "m", "--check") == 1
        assert "stale" in capsys.readouterr().out

    def test_run_get_describe_logs(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        rc = run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30")
        out = capsys.readouterr().out
        assert rc == 0
        assert "TPUJobSucceeded" in out
        assert "schedule-to-first-step latency" in out

        rc = run_cli("--state-dir", state, "get")
        out = capsys.readouterr().out
        assert rc == 0
        assert "cli-job" in out and "Succeeded" in out

        rc = run_cli("--state-dir", state, "describe", "cli-job")
        out = capsys.readouterr().out
        assert rc == 0
        assert "TPUJobCreated" in out  # events section
        assert "Master: desired=1" in out
        assert "Timeline:" in out  # lifecycle spans (SURVEY.md §5 tracing)
        assert "total (submit -> finished)" in out

        rc = run_cli("--state-dir", state, "logs", "cli-job")
        out = capsys.readouterr().out
        assert rc == 0
        assert "[noop]" in out

        rc = run_cli("--state-dir", state, "delete", "cli-job")
        assert rc == 0
        rc = run_cli("--state-dir", state, "get", "cli-job")
        assert rc == 1

    def test_logs_follow_streams_until_finish(self, tmp_path, capsys):
        """kubectl logs -f analog: stream output of a live job, return when
        it finishes."""
        import sys as _sys
        import threading

        from pytorch_operator_tpu.api import load_job
        from pytorch_operator_tpu.controller.supervisor import Supervisor

        state = tmp_path / "state"
        spec = tmp_path / "slow.yaml"
        spec.write_text(
            f"""
metadata: {{name: slowjob}}
spec:
  replica_specs:
    Master:
      template:
        command: [{_sys.executable!r}, "-c", "import time; print('early', flush=True); time.sleep(2); print('late', flush=True)"]
"""
        )
        sup = Supervisor(state_dir=state)
        t = threading.Thread(target=lambda: sup.run(load_job(spec), timeout=60))
        t.start()
        try:
            # Wait for the log file to exist, then follow it to completion.
            import time as _time

            deadline = _time.time() + 30
            while not list((state / "logs").glob("*.log")):
                assert _time.time() < deadline, "job never started"
                _time.sleep(0.1)
            rc = run_cli("--state-dir", state, "logs", "slowjob", "--follow")
            out = capsys.readouterr().out
            assert rc == 0
            assert "early" in out and "late" in out
        finally:
            t.join(timeout=30)
            sup.shutdown()

    def test_run_invalid_spec(self, tmp_path, capsys):
        bad = tmp_path / "bad.yaml"
        bad.write_text("metadata: {name: bad}\nspec: {replica_specs: {Worker: {template: {module: m}}}}\n")
        rc = run_cli("--state-dir", tmp_path / "s", "run", bad)
        err = capsys.readouterr().err
        assert rc == 2
        assert "Master" in err

    def test_run_failing_job_exit_code(self, tmp_path, capsys):
        y = tmp_path / "f.yaml"
        y.write_text(
            """
metadata: {name: failer}
spec:
  replica_specs:
    Master:
      restart_policy: Never
      template:
        module: pytorch_operator_tpu.workloads.exit_with
        args: ["--code", "5"]
"""
        )
        rc = run_cli("--state-dir", tmp_path / "s", "run", y, "--timeout", "30")
        assert rc == 1

    def test_submit_then_get(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        rc = run_cli("--state-dir", state, "submit", job_yaml)
        assert rc == 0
        rc = run_cli("--state-dir", state, "get")
        out = capsys.readouterr().out
        assert "cli-job" in out and "Pending" in out

    def test_unknown_job_errors(self, tmp_path, capsys):
        assert run_cli("--state-dir", tmp_path / "s", "describe", "ghost") == 1
        assert run_cli("--state-dir", tmp_path / "s", "logs", "ghost") == 1
        assert run_cli("--state-dir", tmp_path / "s", "delete", "ghost") == 1
        assert (
            run_cli("--state-dir", tmp_path / "s", "scale", "ghost", "--workers", "2")
            == 1
        )

    def test_scale_writes_marker_and_validates(self, tmp_path, capsys):
        y = tmp_path / "e.yaml"
        y.write_text(
            """
metadata: {name: el}
spec:
  replica_specs:
    Master:
      template: {module: pytorch_operator_tpu.workloads.noop}
    Worker:
      replicas: 1
      template: {module: pytorch_operator_tpu.workloads.noop}
  elastic_policy: {min_replicas: 1, max_replicas: 3}
"""
        )
        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "submit", y) == 0
        # out of bounds → rejected client-side
        assert run_cli("--state-dir", state, "scale", "el", "--workers", "9") == 2
        assert run_cli("--state-dir", state, "scale", "el", "--workers", "2") == 0
        marker = state / "jobs" / "default_el.scale"
        assert marker.read_text() == "2"

    def test_scale_requires_elastic_policy(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "submit", job_yaml) == 0
        assert run_cli("--state-dir", state, "scale", "cli-job", "--workers", "2") == 2


class TestTrainingTelemetry:
    def test_describe_shows_training_block_for_resnet(self, tmp_path, capsys):
        """VERDICT r2 Missing #1 'done' criterion: `tpujob describe` of a
        resnet job answers "how fast is my job training" — live steps/sec
        + images/sec/chip from the workload's progress heartbeats (the
        same records shown while running; last-known after completion)."""
        state = tmp_path / "state"
        yml = tmp_path / "resnet.yaml"
        yml.write_text(
            """
api_version: tpujob.dev/v1
kind: TPUJob
metadata: {name: resnet-meter}
spec:
  replica_specs:
    Master:
      replicas: 1
      template:
        module: pytorch_operator_tpu.workloads.resnet_bench
        args: ["--depth", "18", "--batch-size", "8", "--image-size", "32",
               "--classes", "10", "--steps", "2", "--warmup", "1",
               "--windows", "2"]
        resources: {cpu_devices: 1}
"""
        )
        assert run_cli("--state-dir", state, "run", str(yml), "--timeout", "300") == 0
        capsys.readouterr()
        assert run_cli("--state-dir", state, "describe", "resnet-meter") == 0
        out = capsys.readouterr().out
        assert "Training:" in out
        assert "Steps/sec:" in out
        assert "images/sec/chip" in out
        # The meter reports a real positive rate from a real window.
        rate = next(
            float(ln.split()[1])
            for ln in out.splitlines()
            if ln.strip().startswith("Throughput:")
        )
        assert rate > 0


class TestEvents:
    def test_events_merged_across_jobs(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()
        assert run_cli("--state-dir", state, "events") == 0
        out = capsys.readouterr().out
        assert "TPUJobSubmitted" in out
        assert "TPUJobSucceeded" in out
        assert "REASON" in out  # header

    def test_events_tail_bounds_output(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()
        assert run_cli("--state-dir", state, "events", "--tail", "1") == 0
        out = capsys.readouterr().out.strip().splitlines()
        assert len(out) == 2  # header + exactly one event

    def test_events_empty_state(self, tmp_path, capsys):
        assert run_cli("--state-dir", tmp_path / "fresh", "events") == 0
        assert "no events" in capsys.readouterr().out

    def test_events_name_filters_to_one_job(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        other = tmp_path / "other.yaml"
        other.write_text(
            "metadata: {name: other-job}\n"
            "spec:\n  replica_specs:\n    Master:\n"
            "      template: {module: pytorch_operator_tpu.workloads.noop}\n"
        )
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        assert run_cli("--state-dir", state, "run", other, "--timeout", "30") == 0
        capsys.readouterr()
        assert run_cli("--state-dir", state, "events", "cli-job") == 0
        out = capsys.readouterr().out
        assert "cli-job" in out and "other-job" not in out

    def test_events_follow_drains_then_exits_on_finished_job(
        self, tmp_path, job_yaml, capsys
    ):
        """--follow on an already-finished job: one full aggregation-aware
        drain, then exit 0 (the live-tail loop ends when the job record
        finishes — crash-loop debugging without re-running describe)."""
        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()
        assert run_cli("--state-dir", state, "events", "cli-job", "--follow") == 0
        out = capsys.readouterr().out
        assert "TPUJobSubmitted" in out
        assert "TPUJobSucceeded" in out

    def test_events_follow_requires_name(self, tmp_path, capsys):
        assert run_cli("--state-dir", tmp_path / "s", "events", "--follow") == 2
        assert "requires a job NAME" in capsys.readouterr().err


class TestTop:
    def test_top_once_renders_fleet_table(self, tmp_path, job_yaml, capsys):
        state = tmp_path / "state"
        assert run_cli("--state-dir", state, "run", job_yaml, "--timeout", "30") == 0
        capsys.readouterr()
        assert run_cli("--state-dir", state, "top", "--once") == 0
        out = capsys.readouterr().out
        # Finished jobs are noise on a live screen: header renders, the
        # succeeded job does not.
        assert "CKPT LAG" in out and "STEPS/S" in out
        assert "(no active jobs)" in out


class TestEventRecorder:
    def test_consecutive_duplicates_aggregate_with_count(self, tmp_path):
        """k8s-style aggregation: a restart-looping job must not grow the
        event log (memory OR sink file) without bound — but the sink
        (the only thing the CLI reads) must still learn the live count,
        via O(log n) count-doubling flushes merged on read."""
        import json
        import math

        from pytorch_operator_tpu.controller.events import (
            EventRecorder,
            merge_event_records,
        )

        rec = EventRecorder(sink_dir=tmp_path / "events")
        for _ in range(500):
            rec.warning("default/loop", "TPUJobRestarting", "restarting replica(s) x.")
        evs = rec.for_job("default/loop")
        assert len(evs) == 1
        assert evs[0].count == 500
        sink = tmp_path / "events" / "default_loop.events.jsonl"
        lines = sink.read_text().splitlines()
        # First occurrence + one flush per count-doubling (2,4,...,256).
        assert len(lines) <= 2 + math.ceil(math.log2(500))
        merged = merge_event_records([json.loads(ln) for ln in lines])
        assert len(merged) == 1
        # The flushed count is at most one doubling behind the truth.
        assert merged[0]["count"] >= 256
        assert merged[0]["timestamp"] >= evs[0].timestamp - 30.0

    def test_aggregated_count_reaches_cli_surface(self, tmp_path, capsys):
        """ADVICE r2: a crash-looping job's repeated warning used to show
        count=1 with the first occurrence's timestamp in `tpujob events`/
        `describe` forever (aggregation was in-memory only)."""
        from pytorch_operator_tpu.controller.events import EventRecorder

        state = tmp_path / "state"
        rec = EventRecorder(sink_dir=state / "events")
        for _ in range(10):
            rec.warning("default/loopy", "BackOff", "replica restarting")
        assert run_cli("--state-dir", state, "events") == 0
        out = capsys.readouterr().out
        # One merged row, carrying the (at most one doubling stale) count.
        assert out.count("BackOff") == 1
        assert "(x8)" in out

    def test_merge_sums_across_recorder_incarnations(self, tmp_path):
        """A supervisor restart resets the in-memory recorder, so the sink
        gains a fresh count=1 run for the same repeating event. The merge
        must SUM incarnations (count reset = new incarnation), not let the
        newest count=1 record swallow the prior incarnation's evidence."""
        import json

        from pytorch_operator_tpu.controller.events import (
            EventRecorder,
            merge_event_records,
        )

        for _ in range(2):  # two recorder incarnations, same sink
            rec = EventRecorder(sink_dir=tmp_path / "events")
            for _ in range(10):
                rec.warning("default/ha", "BackOff", "replica restarting")
        sink = tmp_path / "events" / "default_ha.events.jsonl"
        merged = merge_event_records(
            [json.loads(ln) for ln in sink.read_text().splitlines()]
        )
        assert len(merged) == 1
        # Each incarnation's flushed view is at most one doubling behind
        # its true 10 (= 8); the runs must add: 8 + 8.
        assert merged[0]["count"] == 16

    def test_malformed_sink_lines_skipped_not_fatal(self, tmp_path, capsys):
        """One torn/foreign sink line must not abort `tpujob events` or
        `describe` — including valid-JSON-but-wrong-shape lines (non-dict,
        non-numeric count)."""
        from pytorch_operator_tpu.controller.events import load_merged_events

        state = tmp_path / "state"
        ev_dir = state / "events"
        ev_dir.mkdir(parents=True)
        sink = ev_dir / "default_j.events.jsonl"
        sink.write_text(
            '{"timestamp": 1.0, "type": "Normal", "reason": "Ok", "message": "m"}\n'
            "not json at all\n"
            "42\n"
            "[1, 2]\n"
            '{"timestamp": 2.0, "count": "x", "reason": "Bad"}\n'
            '{"timestamp": 3.0, "type": "Warning", "reason": "Kept", "message": "n"}\n'
        )
        merged = load_merged_events(sink)
        assert [r["reason"] for r in merged] == ["Ok", "Kept"]
        assert run_cli("--state-dir", state, "events") == 0
        out = capsys.readouterr().out
        assert "Ok" in out and "Kept" in out
        assert load_merged_events(ev_dir / "missing.jsonl") == []

    def test_distinct_events_interleave_unmerged(self, tmp_path):
        """Aggregation is consecutive-only (k8s semantics): A,B,A stays
        three records, and the reader merge must not collapse them."""
        from pytorch_operator_tpu.controller.events import (
            EventRecorder,
            merge_event_records,
        )

        rec = EventRecorder(sink_dir=tmp_path / "events")
        rec.normal("default/j", "A", "m")
        rec.normal("default/j", "B", "m")
        rec.normal("default/j", "A", "m")
        assert [e.reason for e in rec.for_job("default/j")] == ["A", "B", "A"]
        import json

        sink = tmp_path / "events" / "default_j.events.jsonl"
        recs = [json.loads(ln) for ln in sink.read_text().splitlines()]
        assert [r["reason"] for r in merge_event_records(recs)] == ["A", "B", "A"]

    def test_memory_cap_keeps_newest(self, tmp_path):
        from pytorch_operator_tpu.controller.events import (
            MAX_EVENTS_PER_JOB,
            EventRecorder,
        )

        rec = EventRecorder()
        for i in range(MAX_EVENTS_PER_JOB + 50):
            rec.normal("default/busy", "R", f"msg {i}")  # all distinct
        evs = rec.for_job("default/busy")
        assert len(evs) == MAX_EVENTS_PER_JOB
        assert evs[-1].message == f"msg {MAX_EVENTS_PER_JOB + 49}"

    def test_drop_job_removes_sink_file(self, tmp_path):
        """A resubmitted incarnation's describe must not open with the
        deleted incarnation's history."""
        from pytorch_operator_tpu.controller.events import EventRecorder

        rec = EventRecorder(sink_dir=tmp_path / "events")
        rec.warning("default/gone", "TPUJobFailed", "boom")
        sink = tmp_path / "events" / "default_gone.events.jsonl"
        assert sink.exists()
        rec.drop_job("default/gone")
        assert not sink.exists()
        assert rec.for_job("default/gone") == []

    def test_sink_write_failure_does_not_raise(self, tmp_path):
        """The sink is a best-effort mirror: an unwritable events dir must
        not crash the reconcile path (the daemon's crash teardown would
        kill live training worlds over a log line)."""
        from pytorch_operator_tpu.controller.events import EventRecorder

        blocked = tmp_path / "events"
        blocked.write_text("a file where the dir should be")
        rec = EventRecorder(sink_dir=blocked)
        rec.normal("default/ok", "R", "m")  # must not raise
        assert rec.for_job("default/ok")[0].reason == "R"
