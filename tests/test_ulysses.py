"""Ulysses (all-to-all) sequence parallelism numerics.

Validates parallel/ulysses.py against the dense oracle on the 8-device
CPU mesh — the second long-context scheme next to ring attention
(complementary trade: 2 collectives and full-S scores per local head vs
ring's P rotations and blockwise scores).
"""

import tests.jaxenv  # noqa: F401  (forces the CPU backend first)

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_operator_tpu.parallel import make_mesh
from pytorch_operator_tpu.parallel.ring import _single_shard
from pytorch_operator_tpu.parallel.ulysses import ulysses_self_attention

# Fast-lane exclusion (-m 'not slow'): sp-mesh training runs.
pytestmark = pytest.mark.slow


def _qkv(B=2, S=32, K=4, G=2, D=8, dtype=jnp.float32, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(B, S, K, G, D)), dtype)
    k = jnp.asarray(rng.normal(size=(B, S, K, D)), dtype)
    v = jnp.asarray(rng.normal(size=(B, S, K, D)), dtype)
    pos = jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S))
    return q, k, v, pos


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("sp", [2, 4])
def test_ulysses_matches_dense_oracle(causal, sp):
    q, k, v, pos = _qkv()
    mesh = make_mesh(f"dp={8 // sp},sp={sp}")
    ref = _single_shard(q, k, v, pos, causal=causal)
    out = jax.jit(
        lambda q, k, v, p: ulysses_self_attention(q, k, v, p, mesh, causal=causal)
    )(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_ulysses_matches_ring():
    """The two sequence-parallel schemes are the same math executed
    differently — identical outputs on the same mesh."""
    from pytorch_operator_tpu.parallel import ring_self_attention

    q, k, v, pos = _qkv()
    mesh = make_mesh("dp=2,sp=4")
    a = jax.jit(lambda *t: ulysses_self_attention(*t, mesh))(q, k, v, pos)
    b = jax.jit(lambda *t: ring_self_attention(*t, mesh))(q, k, v, pos)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-5)


def test_ulysses_falls_back_when_seq_indivisible():
    """S % sp != 0 is a runtime-shape condition (ragged last batch):
    take the single-shard path, not raise."""
    q, k, v, pos = _qkv(S=30)
    mesh = make_mesh("sp=4", devices=jax.devices()[:4])
    out = ulysses_self_attention(q, k, v, pos, mesh)
    ref = _single_shard(q, k, v, pos, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ulysses_indivisible_kv_heads_raise():
    """K % sp != 0 is a STATIC config error: a silent dense fallback at
    the long contexts ulysses exists for would lose the whole win while
    the operator believes sp is active."""
    q, k, v, pos = _qkv(K=2)
    mesh = make_mesh("sp=4", devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="n_kv_heads"):
        ulysses_self_attention(q, k, v, pos, mesh)


def test_ulysses_degenerate_mesh_no_sp_axis():
    q, k, v, pos = _qkv()
    mesh = make_mesh("dp=8")
    out = ulysses_self_attention(q, k, v, pos, mesh)
    ref = _single_shard(q, k, v, pos, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_ulysses_gradients_match_dense():
    q, k, v, pos = _qkv(S=16)
    mesh = make_mesh("sp=2", devices=jax.devices()[:2])

    def loss_u(q, k, v):
        return (
            ulysses_self_attention(q, k, v, pos, mesh).astype(jnp.float32) ** 2
        ).mean()

    def loss_d(q, k, v):
        return (_single_shard(q, k, v, pos, causal=True).astype(jnp.float32) ** 2).mean()

    gu = jax.grad(loss_u, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gu, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-5)


def test_llama_ulysses_equals_dense_logits():
    """The full model produces the same logits under attn_impl='ulysses'."""
    from pytorch_operator_tpu.models.llama import Llama, llama_tiny

    mesh = make_mesh("fsdp=2,sp=2,tp=2")
    tokens = jnp.asarray(
        np.random.default_rng(1).integers(0, 256, size=(2, 16)), jnp.int32
    )
    dense = Llama(llama_tiny())
    variables = dense.init(jax.random.key(0), tokens)
    ref = dense.apply(variables, tokens)
    uly = Llama(llama_tiny(attn_impl="ulysses"), mesh=mesh)
    out = jax.jit(lambda v, t: uly.apply(v, t))(variables, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-4)


def test_llama_ulysses_trains_on_sp_mesh():
    """End-to-end through the workload: dp×sp train with ulysses matches
    the dense sequential run's loss (same seed, same data)."""
    from pytorch_operator_tpu.workloads import llama_train

    kw = dict(
        config="tiny", batch_size=8, seq_len=32, steps=2, warmup=1,
        xent_impl="chunked", log=lambda *_: None,
    )
    uly = llama_train.run(mesh_spec="dp=2,sp=2,tp=2", attn_impl="ulysses", **kw)
    ref = llama_train.run(mesh_spec="dp=8", attn_impl="dense", **kw)
    assert uly["final_loss"] == pytest.approx(ref["final_loss"], abs=1e-3)
