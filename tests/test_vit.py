"""ViT model family + benchmark workload."""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import vit as vit_lib
from pytorch_operator_tpu.parallel import make_mesh

# Fast-lane exclusion (-m 'not slow'): real ViT training/remat runs.
pytestmark = pytest.mark.slow


def tiny_cfg(**over):
    return vit_lib.ViTConfig(
        **{
            "image_size": 16,
            "patch_size": 4,
            "num_classes": 10,
            "d_model": 32,
            "depth": 2,
            "n_heads": 2,
            "d_ff": 64,
            "dtype": np.float32,
            **over,
        }
    )


class TestViTModel:
    def test_forward_shape_and_finite(self):
        import jax
        import jax.numpy as jnp

        cfg = tiny_cfg()
        model = vit_lib.ViT(cfg)
        x = jnp.asarray(
            np.random.default_rng(0).standard_normal((3, 16, 16, 3)),
            jnp.float32,
        )
        params = model.init(jax.random.key(0), x)["params"]
        logits = model.apply({"params": params}, x)
        assert logits.shape == (3, 10)
        assert bool(jnp.isfinite(logits).all())

    def test_flash_attention_matches_dense(self):
        """attn_impl='flash' (pallas interpret mode on CPU) must agree
        with the dense path given identical params."""
        import jax
        import jax.numpy as jnp

        dense = vit_lib.ViT(tiny_cfg())
        flash = vit_lib.ViT(tiny_cfg(attn_impl="flash"))
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((2, 16, 16, 3)),
            jnp.float32,
        )
        params = dense.init(jax.random.key(0), x)["params"]
        yd = dense.apply({"params": params}, x)
        yf = flash.apply({"params": params}, x)
        np.testing.assert_allclose(
            np.asarray(yd), np.asarray(yf), rtol=2e-4, atol=2e-4
        )

    def test_remat_same_numerics_less_backward_memory(self):
        """cfg.remat must not change the math (same loss/grads) while
        cutting the compiled backward's activation residency — the lever
        that unlocks larger ViT batches (VERDICT r2 Weak #2)."""
        import jax
        import jax.numpy as jnp
        import optax

        x = jnp.asarray(
            np.random.default_rng(0).random((64, 16, 16, 3), np.float32)
        )
        y = jnp.asarray(np.arange(64) % 10, np.int32)
        results = {}
        for remat in (False, True):
            cfg = tiny_cfg(depth=6, remat=remat)
            model = vit_lib.ViT(cfg)
            params = jax.tree.map(
                lambda l: l.unbox() if hasattr(l, "unbox") else l,
                model.init(jax.random.key(0), x[:1])["params"],
                is_leaf=lambda l: hasattr(l, "unbox"),
            )

            def loss_fn(p, _model=model):
                logits = _model.apply({"params": p}, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()

            g = jax.jit(jax.value_and_grad(loss_fn))
            loss, grads = g(params)
            ma = g.lower(params).compile().memory_analysis()
            results[remat] = (float(loss), grads, ma)
        l0, g0, ma0 = results[False]
        l1, g1, ma1 = results[True]
        assert l0 == pytest.approx(l1, rel=1e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6
            ),
            g0,
            g1,
        )
        if ma0 is not None and ma1 is not None:
            assert ma1.temp_size_in_bytes < ma0.temp_size_in_bytes, (
                ma1.temp_size_in_bytes,
                ma0.temp_size_in_bytes,
            )

    def test_remat_dots_policy_same_numerics_between_full_and_none(self):
        """remat_policy='dots' (save GEMM outputs, recompute the rest)
        must match no-remat numerics exactly, with backward residency
        between no-remat and full remat."""
        import jax
        import jax.numpy as jnp
        import optax

        x = jnp.asarray(
            np.random.default_rng(1).random((32, 16, 16, 3), np.float32)
        )
        y = jnp.asarray(np.arange(32) % 10, np.int32)
        results = {}
        for tag, kw in {
            "none": dict(remat=False),
            "dots": dict(remat=True, remat_policy="dots"),
            "full": dict(remat=True, remat_policy="full"),
        }.items():
            cfg = tiny_cfg(depth=6, **kw)
            model = vit_lib.ViT(cfg)
            params = jax.tree.map(
                lambda l: l.unbox() if hasattr(l, "unbox") else l,
                model.init(jax.random.key(0), x[:1])["params"],
                is_leaf=lambda l: hasattr(l, "unbox"),
            )

            def loss_fn(p, _model=model):
                logits = _model.apply({"params": p}, x)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits, y
                ).mean()

            g = jax.jit(jax.value_and_grad(loss_fn))
            loss, _ = g(params)
            ma = g.lower(params).compile().memory_analysis()
            results[tag] = (float(loss), ma)
        losses = {t: l for t, (l, _) in results.items()}
        assert len(set(losses.values())) == 1, losses
        temps = {t: ma.temp_size_in_bytes for t, (_, ma) in results.items() if ma}
        if len(temps) == 3:
            assert temps["full"] <= temps["dots"] <= temps["none"], temps

    def test_trains_loss_decreases(self):
        import jax

        from pytorch_operator_tpu.workloads.vit_bench import run_benchmark

        result = run_benchmark(
            variant="s16",
            batch_size=8,
            image_size=16,
            classes=10,
            steps=6,
            warmup=1,
            lr=1e-3,
            log=lambda *_: None,
        )
        assert np.isfinite(result["final_loss"])
        # Label-smoothed chance level for 10 classes is ~2.3; six AdamW
        # steps on a fixed synthetic batch must beat it.
        assert result["final_loss"] < 2.3

    def test_trains_from_packed_image_file(self, tmp_path):
        """Real-data path: packed images stream through the prefetch
        loader; image geometry comes from the file."""
        import numpy as np_

        from pytorch_operator_tpu.data import pack_arrays
        from pytorch_operator_tpu.workloads.vit_bench import run_benchmark

        rng = np_.random.default_rng(0)
        x = rng.standard_normal((32, 16, 16, 3), dtype=np_.float32)
        y = rng.integers(0, 10, size=(32,), dtype=np_.int32)
        f = tmp_path / "imgs.bin"
        pack_arrays(f, {"x": x, "y": y})

        result = run_benchmark(
            variant="s16",
            batch_size=8,
            classes=10,
            steps=4,
            warmup=1,
            data_file=str(f),
            log=lambda *_: None,
        )
        assert result["input"] == "file"
        assert np.isfinite(result["final_loss"])

    def test_shards_on_fsdp_tp_mesh(self):
        """The LM-stack logical annotations carry over: encoder q_proj
        kernels land (embed=fsdp, heads=tp)-sharded abstractly."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel import logical_shardings

        mesh = make_mesh("fsdp=4,tp=2")
        cfg = tiny_cfg(n_heads=2)
        model = vit_lib.ViT(cfg)

        abstract = jax.eval_shape(
            lambda k: model.init(k, jnp.zeros((1, 16, 16, 3))),
            jax.random.key(0),
        )
        sh = logical_shardings(abstract, mesh)
        q = sh["params"]["layers"]["q_proj"]["kernel"]
        assert "fsdp" in tuple(q.spec) and "tp" in tuple(q.spec), q
