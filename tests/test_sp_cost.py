"""Ring-vs-Ulysses communication cost, measured in-tree (VERDICT r3
Weak #4 / Next #8): the scheme-selection guidance in PARITY.md is backed
by counted collectives, not textbook assertion.

Counts come from ops.flop_count.count_collectives (abstract trace — a
32k-sequence program costs nothing to count). Structure pinned here, on
train-step-shaped calls (attention fwd + bwd through jax.grad):

- ring: 5P ppermutes per attention (3P forward k/v/pos rotations + 2P
  backward cotangent rotations), each a LATENCY-bound neighbor hop that
  must hide behind one attention block's math; per-device payload is
  P-INDEPENDENT (the full K+V cycles through every chip).
- ulysses: exactly 8 all_to_alls regardless of P and S (3 in + 1 out,
  doubled by the transpose), and per-device payload SHRINKS ~1/P (the
  head dimension is the resharding currency).

Hence the guidance: ring when S is extreme (fat blocks hide P hops,
no head-divisibility constraint); ulysses when kv-heads are plentiful
and S moderate (fewer, bandwidth-friendly collectives, shrinking
per-chip bytes).
"""

from __future__ import annotations

import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.ops.flop_count import count_collectives
from pytorch_operator_tpu.parallel import make_mesh

B, K, G, D = 1, 8, 4, 64


def _profile(scheme: str, sp: int, S: int):
    import jax
    import jax.numpy as jnp

    from pytorch_operator_tpu.parallel.ring import ring_self_attention
    from pytorch_operator_tpu.parallel.ulysses import ulysses_self_attention

    mesh = make_mesh(f"sp={sp}", devices=jax.devices()[:sp])
    attn = ring_self_attention if scheme == "ring" else ulysses_self_attention
    q = jnp.zeros((B, S, K, G, D), jnp.bfloat16)
    k = jnp.zeros((B, S, K, D), jnp.bfloat16)
    v = jnp.zeros((B, S, K, D), jnp.bfloat16)
    pos = jnp.zeros((B, S), jnp.int32)

    def f(q, k, v):
        return attn(q, k, v, pos, mesh).astype(jnp.float32).sum()

    return count_collectives(jax.grad(f, argnums=(0, 1, 2)), q, k, v)


class TestSpCommStructure:
    @pytest.mark.parametrize("sp", [4, 8])
    @pytest.mark.parametrize("S", [4096, 32768])
    def test_ring_is_5p_ppermutes_with_p_independent_bytes(self, sp, S):
        c = _profile("ring", sp, S)
        assert set(c.calls) == {"ppermute"}, c.calls
        assert round(c.calls["ppermute"]) == 5 * sp, c.calls
        # Full K+V (+pos, + their cotangents) cycle through every device:
        # payload per device does not depend on the ring size.
        ref = _profile("ring", 4, S)
        assert c.total_bytes == pytest.approx(ref.total_bytes, rel=1e-6)

    @pytest.mark.parametrize("sp", [4, 8])
    @pytest.mark.parametrize("S", [4096, 32768])
    def test_ulysses_is_8_all_to_alls_independent_of_p_and_s(self, sp, S):
        c = _profile("ulysses", sp, S)
        assert set(c.calls) == {"all_to_all"}, c.calls
        assert round(c.calls["all_to_all"]) == 8, c.calls

    def test_ulysses_bytes_shrink_with_p_ring_bytes_do_not(self):
        u4 = _profile("ulysses", 4, 4096)
        u8 = _profile("ulysses", 8, 4096)
        r4 = _profile("ring", 4, 4096)
        r8 = _profile("ring", 8, 4096)
        assert u8.total_bytes == pytest.approx(u4.total_bytes / 2, rel=1e-6)
        assert r8.total_bytes == pytest.approx(r4.total_bytes, rel=1e-6)

    def test_bytes_scale_linearly_with_sequence(self):
        for scheme in ("ring", "ulysses"):
            small = _profile(scheme, 4, 4096)
            big = _profile(scheme, 4, 32768)
            assert big.total_bytes == pytest.approx(
                8 * small.total_bytes, rel=0.05
            ), scheme
