"""Parallel-layer tests on the 8-device virtual CPU mesh: mesh specs,
logical sharding rules, FSDP auto-sharding, collectives under shard_map.
"""

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401  (forces CPU platform before jax use)
from pytorch_operator_tpu.jaxcompat import shard_map
from pytorch_operator_tpu.parallel import (
    collectives,
    fsdp_spec,
    fsdp_shardings,
    logical_to_spec,
    make_mesh,
    parse_mesh_spec,
    resolve_axis_sizes,
)


class TestMeshSpec:
    def test_parse_string(self):
        assert parse_mesh_spec("dp=2,tp=4") == {"dp": 2, "tp": 4}

    def test_wildcard_resolution(self):
        assert resolve_axis_sizes("fsdp=-1,tp=2", 8) == {"fsdp": 4, "tp": 2}

    def test_canonical_order(self):
        axes = resolve_axis_sizes({"tp": 2, "dp": 4}, 8)
        assert list(axes.keys()) == ["dp", "tp"]  # tp innermost

    def test_product_mismatch_rejected(self):
        with pytest.raises(ValueError, match="!= device count"):
            resolve_axis_sizes("dp=3", 8)

    def test_bad_axis_rejected(self):
        with pytest.raises(ValueError, match="unknown mesh axis"):
            parse_mesh_spec("zz=2")

    def test_two_wildcards_rejected(self):
        with pytest.raises(ValueError, match="one -1 wildcard"):
            parse_mesh_spec("dp=-1,tp=-1")

    def test_make_mesh(self):
        mesh = make_mesh("dp=2,fsdp=2,tp=2")
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert mesh.devices.shape == (2, 2, 2)


class TestHybridMesh:
    """Multi-slice meshes: dcn axes outermost, ici axes within a slice."""

    def test_axes_and_shape(self):
        from pytorch_operator_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh(ici="fsdp=-1,tp=2", dcn="dp=2")
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert mesh.devices.shape == (2, 2, 2)
        # dcn outermost: each dp row holds one contiguous 4-device "slice".
        flat = mesh.devices.reshape(2, -1)
        ids = [[d.id for d in row] for row in flat]
        assert ids[0] == sorted(ids[0]) and ids[1] == sorted(ids[1])
        assert max(ids[0]) < min(ids[1])

    def test_gradient_psum_over_dcn_axis(self):
        """The intended layout: fsdp/tp traffic inside a slice, one dp
        gradient reduction across DCN — exercised with a real psum."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pytorch_operator_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh(ici="fsdp=4", dcn="dp=2")
        x = jnp.arange(8.0).reshape(8, 1)
        xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"))))
        total = jax.jit(lambda a: a.sum())(xs)
        assert float(total) == sum(range(8))

    def test_overlapping_axes_rejected(self):
        from pytorch_operator_tpu.parallel import make_hybrid_mesh

        with pytest.raises(ValueError, match="both"):
            make_hybrid_mesh(ici="dp=4", dcn="dp=2")

    def test_dcn_wildcard_rejected(self):
        from pytorch_operator_tpu.parallel import make_hybrid_mesh

        with pytest.raises(ValueError, match="explicit"):
            make_hybrid_mesh(ici="fsdp=4", dcn="dp=-1")

    def test_empty_dcn_degrades_to_plain_mesh(self):
        from pytorch_operator_tpu.parallel import make_hybrid_mesh

        mesh = make_hybrid_mesh(ici="dp=-1", dcn="")
        assert mesh.devices.shape == (8,)

    def test_at_dcn_suffix_in_make_mesh(self):
        """The --mesh / TPUJOB_MESH user syntax for hybrid layouts."""
        mesh = make_mesh("dp=2@dcn,fsdp=-1,tp=2")
        assert mesh.axis_names == ("dp", "fsdp", "tp")
        assert mesh.devices.shape == (2, 2, 2)

    def test_all_dcn_spec(self):
        """Pure cross-slice data parallel: one device per slice, no
        phantom ici axes."""
        mesh = make_mesh("dp=8@dcn")
        assert mesh.axis_names == ("dp",)
        assert mesh.devices.shape == (8,)

    def test_all_dcn_spec_with_leftover_devices_rejected(self):
        from pytorch_operator_tpu.parallel import make_hybrid_mesh

        with pytest.raises(ValueError, match="1 device per slice"):
            make_hybrid_mesh(ici="", dcn="dp=2")

    def test_parse_mesh_spec_accepts_dcn_suffix(self):
        """The canonical parser must not choke on the documented syntax."""
        assert parse_mesh_spec("dp=2@dcn,tp=2") == {"dp": 2, "tp": 2}
        from pytorch_operator_tpu.parallel.mesh import split_hybrid_spec

        assert split_hybrid_spec("dp=2@dcn,fsdp=-1,tp=2") == ("fsdp=-1,tp=2", "dp=2")


class TestShardingRules:
    def test_logical_to_spec(self):
        mesh = make_mesh("dp=2,tp=4")
        spec = logical_to_spec(("batch", "seq", "heads"), mesh=mesh)
        assert tuple(spec) == ("dp", None, "tp")

    def test_missing_mesh_axis_replicates(self):
        mesh = make_mesh("dp=8")
        spec = logical_to_spec(("batch", "mlp"), mesh=mesh)  # no tp axis
        assert tuple(spec) == ("dp",)

    def test_fsdp_spec_picks_divisible_dim(self):
        mesh = make_mesh("fsdp=4,tp=2")
        spec = fsdp_spec((333, 1024), mesh)
        assert tuple(spec) == (None, "fsdp")

    def test_fsdp_small_param_replicates(self):
        mesh = make_mesh("fsdp=8")
        assert tuple(fsdp_spec((128,), mesh)) == ()

    def test_fsdp_shardings_tree(self):
        import jax.numpy as jnp

        mesh = make_mesh("fsdp=8")
        params = {"w": jnp.zeros((512, 512)), "b": jnp.zeros((8,))}
        sh = fsdp_shardings(params, mesh, min_elements=1024)
        assert tuple(sh["w"].spec) == ("fsdp",)
        assert tuple(sh["b"].spec) == ()


class TestCollectives:
    def test_psum_ring_reduce_scatter(self):
        import jax
        import jax.numpy as jnp
        from functools import partial
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = make_mesh("dp=8")
        x = jax.device_put(
            jnp.arange(8.0), NamedSharding(mesh, PartitionSpec("dp"))
        )

        @jax.jit
        @partial(
            shard_map,
            mesh=mesh,
            in_specs=PartitionSpec("dp"),
            out_specs=(PartitionSpec(), PartitionSpec("dp"), PartitionSpec("dp")),
        )
        def f(xs):
            total = collectives.psum(jnp.sum(xs), "dp")
            ring = collectives.ring_shift(xs, "dp", shift=1)
            gathered = collectives.all_gather(xs, "dp")
            rs = collectives.reduce_scatter(gathered, "dp")
            return total, ring, rs

        total, ring, rs = f(x)
        assert float(total) == 28.0
        np.testing.assert_array_equal(np.asarray(ring), np.roll(np.arange(8.0), 1))
        # reduce_scatter(all_gather(x)) == x * n? No: psum_scatter of the
        # full gathered vector sums 8 copies then scatters -> x * 8... each
        # shard holds the same gathered vector, so scatter_i = 8 * x_i.
        np.testing.assert_array_equal(np.asarray(rs), np.arange(8.0) * 8)

    def test_axis_index(self):
        import jax
        import jax.numpy as jnp
        from functools import partial
        from jax.sharding import PartitionSpec

        mesh = make_mesh("dp=8")

        @jax.jit
        @partial(
            shard_map, mesh=mesh, in_specs=(), out_specs=PartitionSpec("dp")
        )
        def f():
            return jnp.reshape(collectives.axis_index("dp"), (1,))

        np.testing.assert_array_equal(np.asarray(f()), np.arange(8))
