"""Test fixture builders.

Mirror of the reference's ``pkg/common/util/v1/testutil/`` (SURVEY.md §4
"Fixture library"): helpers that build TPUJob specs with given master/worker
counts, so controller tests stay terse.
"""

from __future__ import annotations

from typing import Optional

from pytorch_operator_tpu.api import (
    CleanPodPolicy,
    ElasticPolicy,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    TPUJob,
    TPUJobSpec,
    set_defaults,
)


def new_job(
    name: str = "test-job",
    workers: int = 1,
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE,
    clean_pod_policy: Optional[CleanPodPolicy] = None,
    backoff_limit: Optional[int] = None,
    active_deadline_seconds: Optional[int] = None,
    ttl_seconds_after_finished: Optional[int] = None,
    elastic: Optional[ElasticPolicy] = None,
    module: str = "pytorch_operator_tpu.workloads.noop",
    defaulted: bool = True,
) -> TPUJob:
    """Build a Master(1) + Worker(N) TPUJob, defaulted unless asked not to."""
    def mk_template() -> ProcessTemplate:
        return ProcessTemplate(module=module)

    spec = TPUJobSpec(
        replica_specs={
            ReplicaType.MASTER: ReplicaSpec(
                replicas=1, restart_policy=restart_policy, template=mk_template()
            ),
        },
        run_policy=RunPolicy(
            clean_pod_policy=clean_pod_policy,
            backoff_limit=backoff_limit,
            active_deadline_seconds=active_deadline_seconds,
            ttl_seconds_after_finished=ttl_seconds_after_finished,
        ),
        elastic_policy=elastic,
    )
    if workers > 0:
        spec.replica_specs[ReplicaType.WORKER] = ReplicaSpec(
            replicas=workers, restart_policy=restart_policy, template=mk_template()
        )
    job = TPUJob(metadata=ObjectMeta(name=name), spec=spec)
    if defaulted:
        set_defaults(job)
    return job
