"""Test fixture builders.

Mirror of the reference's ``pkg/common/util/v1/testutil/`` (SURVEY.md §4
"Fixture library"): helpers that build TPUJob specs with given master/worker
counts, so controller tests stay terse.
"""

from __future__ import annotations

from typing import Optional

from pytorch_operator_tpu.api import (
    CleanPodPolicy,
    ElasticPolicy,
    ObjectMeta,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    TPUJob,
    TPUJobSpec,
    set_defaults,
)


def new_job(
    name: str = "test-job",
    workers: int = 1,
    restart_policy: RestartPolicy = RestartPolicy.ON_FAILURE,
    clean_pod_policy: Optional[CleanPodPolicy] = None,
    backoff_limit: Optional[int] = None,
    active_deadline_seconds: Optional[int] = None,
    ttl_seconds_after_finished: Optional[int] = None,
    elastic: Optional[ElasticPolicy] = None,
    module: str = "pytorch_operator_tpu.workloads.noop",
    defaulted: bool = True,
) -> TPUJob:
    """Build a Master(1) + Worker(N) TPUJob, defaulted unless asked not to."""
    def mk_template() -> ProcessTemplate:
        return ProcessTemplate(module=module)

    spec = TPUJobSpec(
        replica_specs={
            ReplicaType.MASTER: ReplicaSpec(
                replicas=1, restart_policy=restart_policy, template=mk_template()
            ),
        },
        run_policy=RunPolicy(
            clean_pod_policy=clean_pod_policy,
            backoff_limit=backoff_limit,
            active_deadline_seconds=active_deadline_seconds,
            ttl_seconds_after_finished=ttl_seconds_after_finished,
        ),
        elastic_policy=elastic,
    )
    if workers > 0:
        spec.replica_specs[ReplicaType.WORKER] = ReplicaSpec(
            replicas=workers, restart_policy=restart_policy, template=mk_template()
        )
    job = TPUJob(metadata=ObjectMeta(name=name), spec=spec)
    if defaulted:
        set_defaults(job)
    return job


def assert_histogram_conformant(parsed: dict, name: str) -> None:
    """Prometheus histogram exposition invariants for one metric family
    parsed from text (obs.metrics.parse_prometheus_text): at least one
    series; per series, cumulative ``_bucket`` values monotone
    nondecreasing over increasing ``le``; a ``+Inf`` bucket present and
    equal to ``_count``; a ``_sum`` sample present and consistent with
    the observed count (zero iff count is zero, for nonnegative
    latencies)."""
    buckets = parsed.get(f"{name}_bucket") or []
    sums = parsed.get(f"{name}_sum") or []
    counts = parsed.get(f"{name}_count") or []
    assert buckets, f"{name}: no _bucket series in exposition"

    def base_key(labels: dict) -> tuple:
        return tuple(sorted((k, v) for k, v in labels.items() if k != "le"))

    series: dict = {}
    for labels, v in buckets:
        assert "le" in labels, f"{name}_bucket sample without le: {labels}"
        series.setdefault(base_key(labels), []).append((labels["le"], v))
    sum_by = {base_key(l): v for l, v in sums}
    count_by = {base_key(l): v for l, v in counts}
    for key, entries in series.items():
        ordered = sorted(
            (float("inf") if le == "+Inf" else float(le), v)
            for le, v in entries
        )
        bounds = [b for b, _ in ordered]
        assert len(set(bounds)) == len(bounds), f"{name}{key}: duplicate le"
        cums = [v for _, v in ordered]
        assert cums == sorted(cums), f"{name}{key}: buckets not cumulative"
        assert bounds[-1] == float("inf"), f"{name}{key}: no +Inf bucket"
        assert key in count_by, f"{name}{key}: missing _count"
        assert key in sum_by, f"{name}{key}: missing _sum"
        assert cums[-1] == count_by[key], (
            f"{name}{key}: +Inf bucket {cums[-1]} != count {count_by[key]}"
        )
        assert (sum_by[key] == 0) == (count_by[key] == 0) or sum_by[key] >= 0
