"""Data-plane bench smoke lane (``-m bench_smoke``, also tier-1).

Runs the real harness at a small size — few steps, small model, real
orbax saves — pinning the pipelined data-plane invariants long before
anyone reruns the full BENCH_dataplane.json artifact:

- a STAGED save stalls the step loop less than the PR-3 eager-async
  save, which stalls less than a blocking save — all three of the same
  state, all ending sidecar-verified;
- a PREFETCHED loop issues ZERO ``device_put`` calls on the step path
  (the transfers all ride the producer pool);
- a STAGED loop issues ZERO ``device_get`` calls on the step path
  beyond the bench's own loss-fence budget (the state gather rides the
  snapshot-stage thread);
- under a bursty producer the AUTOTUNED feed stalls less than the
  static ``depth=2`` feed, and its depth never exceeds the
  ``depth_max`` budget.
"""

from __future__ import annotations

import json

import pytest

import tests.jaxenv  # noqa: F401  (forces CPU backend with 8 devices)

from pytorch_operator_tpu.workloads import dataplane_bench

pytestmark = pytest.mark.bench_smoke


@pytest.fixture(scope="module")
def smoke_result(tmp_path_factory):
    import os

    from pytorch_operator_tpu.obs import trace as obs_trace

    # The flight-recorder overhead pin below requires tracing OFF: an
    # env leak from an earlier test would void the zero-span invariant.
    os.environ.pop(obs_trace.ENV_VAR, None)
    obs_trace.reset_tracer()
    td = tmp_path_factory.mktemp("dataplane")
    # Small but real: 18 steps, 3 timed saves per cell, ~1.5 MB state.
    # checkpoint_every=6 keeps the save interval clear of the commit
    # time at this size, so the stall ordering measures the submit
    # protocol rather than max_pending backpressure.
    return dataplane_bench.run(
        steps=18, checkpoint_every=6, dim=128, batch=128,
        feed_steps=36,
        work_dir=str(td), log=lambda *_: None,
    )


def cell(result, ckpt, feed):
    return next(
        c for c in result["cells"] if c["ckpt"] == ckpt and c["feed"] == feed
    )


def feed_cell(result, mode):
    return next(
        c for c in result["feed_cells"] if c["feed_cell"] == mode
    )


class TestDataPlaneSmoke:
    def test_async_save_stalls_less_than_blocking(self, smoke_result):
        blocking = cell(smoke_result, "blocking", "inline")
        async_ = cell(smoke_result, "async", "inline")
        # THE tier-1 invariant: on the same state, the async save's
        # step-loop stall must undercut the blocking save's. (The full
        # artifact pins the >=5x ratio; smoke sizes only guarantee the
        # ordering.)
        assert async_["stall_ms_p50"] < blocking["stall_ms_p50"], (
            async_,
            blocking,
        )
        assert blocking["stall_ms_p50"] > 0

    def test_staged_save_stalls_less_than_async(self, smoke_result):
        """The staged pipeline's headline: a fence-only submit undercuts
        the eager host snapshot (the full artifact pins the >=2x ratio
        vs the PR-3 baseline; smoke sizes guarantee the ordering)."""
        async_ = cell(smoke_result, "async", "inline")
        staged = cell(smoke_result, "staged", "inline")
        assert staged["stall_ms_p50"] < async_["stall_ms_p50"], (
            staged,
            async_,
        )

    def test_prefetched_loop_zero_inline_device_puts(self, smoke_result):
        for ckpt in ("blocking", "async", "staged"):
            pf = cell(smoke_result, ckpt, "prefetched")
            inline = cell(smoke_result, ckpt, "inline")
            # Zero transfers on the step path vs one per step inline.
            assert pf["step_thread_device_puts"] == 0, pf
            assert inline["step_thread_device_puts"] == inline["steps"]

    def test_staged_zero_step_thread_gathers_beyond_budget(self, smoke_result):
        """The staged pipeline's transfer pin: the state gather NEVER
        runs on the step thread — device_get calls there are exactly
        the bench's own loss fences. The eager-async cells show the
        contrast: one gather per state leaf per save on the step
        thread."""
        for feed in ("inline", "prefetched"):
            staged = cell(smoke_result, "staged", feed)
            assert staged["step_thread_gets_beyond_budget"] == 0, staged
            eager = cell(smoke_result, "async", feed)
            assert eager["step_thread_gets_beyond_budget"] > 0, eager
        assert (
            smoke_result["comparisons"]["staged_step_thread_gets_beyond_budget"]
            == 0
        )

    def test_every_cell_ends_sidecar_verified(self, smoke_result):
        # Async AND staged saves are first-class VERIFIED checkpoints:
        # the newest verified step equals the newest saved step in
        # every cell.
        for c in smoke_result["cells"]:
            assert c["all_saves_verified"], c
            assert c["last_verified_step"] == c["steps"]
        assert smoke_result["comparisons"]["async_saves_verified"] is True

    def test_autotuned_feed_beats_static_under_bursts(self, smoke_result):
        """The depth-autotune pin: same bursty producer, same step —
        the controller-grown buffer absorbs bursts the static depth=2
        buffer cannot, and never exceeds its budget."""
        static = feed_cell(smoke_result, "static")
        tuned = feed_cell(smoke_result, "autotuned")
        assert tuned["feed_stall_s_total"] < static["feed_stall_s_total"], (
            tuned,
            static,
        )
        # The controller actually acted, inside its budget.
        assert tuned["depth_peak"] > tuned["depth_initial"], tuned
        assert tuned["depth_peak"] <= tuned["depth_max"], tuned
        assert static["depth_peak"] == static["depth_initial"], static
        assert smoke_result["comparisons"]["autotuned_depth_within_max"]

    def test_tracing_disabled_adds_zero_step_path_spans(self, smoke_result):
        """The flight-recorder overhead pin (observability PR): with
        ``TPUJOB_TRACE_DIR`` unset, the fully instrumented step path
        (step spans, save spans, feed-thread spans, queue-wait spans,
        snapshot-stage spans) must emit ZERO span records —
        observability can never quietly tax the hot loop."""
        assert smoke_result["comparisons"]["trace_disabled_zero_spans"] is True
        for c in smoke_result["cells"]:
            assert c["trace_enabled"] is False, c
            assert c["span_records"] == 0, c

    def test_disabled_span_helper_cost_is_noise(self):
        """The ≤1% step-time budget, pinned structurally: a disabled
        ``obs.span`` is one cached None check returning a shared
        nullcontext. Bound its per-call cost at 5 µs — the PR-3 bench's
        steps run ~20 ms, so even a span per step, per save, and per
        feed get stays orders of magnitude under 1%."""
        import time as _time

        from pytorch_operator_tpu import obs

        assert not obs.trace_enabled()
        before = obs.records_emitted()
        n = 50_000
        t0 = _time.perf_counter()
        for _ in range(n):
            with obs.span("step", cat="step"):
                pass
        per_call = (_time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"disabled span helper costs {per_call:.2e}s"
        assert obs.records_emitted() == before

    def test_artifact_shape_is_committed_schema(self, smoke_result, tmp_path):
        out = tmp_path / "bench.json"
        dataplane_bench.run(
            steps=6, checkpoint_every=3, dim=64, batch=32,
            feed_steps=12,
            out=str(out), work_dir=str(tmp_path), log=lambda *_: None,
        )
        data = json.loads(out.read_text())
        assert data["bench"] == "data_plane"
        comp = data["comparisons"]
        for field in (
            "ckpt_stall_p50_reduction",
            "ckpt_stall_p99_reduction",
            "staged_stall_p50_reduction_vs_async",
            "staged_stall_p50_reduction_vs_blocking",
            "steps_per_sec_speedup_async",
            "steps_per_sec_speedup_staged",
            "prefetched_step_thread_puts",
            "staged_step_thread_gets_beyond_budget",
            "async_saves_verified",
            "autotune_steps_per_sec_speedup",
            "autotune_stall_reduction",
            "autotuned_depth_within_max",
        ):
            assert field in comp
        assert comp["async_saves_verified"] is True
        assert {c["feed_cell"] for c in data["feed_cells"]} == {
            "static",
            "autotuned",
        }
