"""Data-plane bench smoke lane (``-m bench_smoke``, also tier-1).

Runs the real harness at a small size — few steps, small model, real
orbax saves — pinning the two data-plane invariants long before anyone
reruns the full BENCH_dataplane.json artifact:

- an ASYNC save stalls the step loop LESS than a blocking save of the
  same state (the whole point of the async writer), while still ending
  sidecar-verified;
- a PREFETCHED loop issues ZERO ``device_put`` calls on the step path
  (the transfers all ride the feed thread).
"""

from __future__ import annotations

import json

import pytest

import tests.jaxenv  # noqa: F401  (forces CPU backend with 8 devices)

from pytorch_operator_tpu.workloads import dataplane_bench

pytestmark = pytest.mark.bench_smoke


@pytest.fixture(scope="module")
def smoke_result(tmp_path_factory):
    import os

    from pytorch_operator_tpu.obs import trace as obs_trace

    # The flight-recorder overhead pin below requires tracing OFF: an
    # env leak from an earlier test would void the zero-span invariant.
    os.environ.pop(obs_trace.ENV_VAR, None)
    obs_trace.reset_tracer()
    td = tmp_path_factory.mktemp("dataplane")
    # Small but real: 15 steps, 3 timed saves per cell, ~1.5 MB state.
    return dataplane_bench.run(
        steps=15, checkpoint_every=5, dim=128, batch=128,
        work_dir=str(td), log=lambda *_: None,
    )


def cell(result, ckpt, feed):
    return next(
        c for c in result["cells"] if c["ckpt"] == ckpt and c["feed"] == feed
    )


class TestDataPlaneSmoke:
    def test_async_save_stalls_less_than_blocking(self, smoke_result):
        blocking = cell(smoke_result, "blocking", "inline")
        async_ = cell(smoke_result, "async", "inline")
        # THE tier-1 invariant: on the same state, the async save's
        # step-loop stall must undercut the blocking save's. (The full
        # artifact pins the >=5x ratio; smoke sizes only guarantee the
        # ordering.)
        assert async_["stall_ms_p50"] < blocking["stall_ms_p50"], (
            async_,
            blocking,
        )
        assert blocking["stall_ms_p50"] > 0

    def test_prefetched_loop_zero_inline_device_puts(self, smoke_result):
        for ckpt in ("blocking", "async"):
            pf = cell(smoke_result, ckpt, "prefetched")
            inline = cell(smoke_result, ckpt, "inline")
            # Zero transfers on the step path vs one per step inline.
            assert pf["step_thread_device_puts"] == 0, pf
            assert inline["step_thread_device_puts"] == inline["steps"]

    def test_every_cell_ends_sidecar_verified(self, smoke_result):
        # Async saves are first-class VERIFIED checkpoints: the newest
        # verified step equals the newest saved step in every cell.
        for c in smoke_result["cells"]:
            assert c["all_saves_verified"], c
            assert c["last_verified_step"] == c["steps"]

    def test_tracing_disabled_adds_zero_step_path_spans(self, smoke_result):
        """The flight-recorder overhead pin (observability PR): with
        ``TPUJOB_TRACE_DIR`` unset, the fully instrumented step path
        (step spans, save spans, feed-thread spans, queue-wait spans)
        must emit ZERO span records — observability can never quietly
        tax the hot loop."""
        assert smoke_result["comparisons"]["trace_disabled_zero_spans"] is True
        for c in smoke_result["cells"]:
            assert c["trace_enabled"] is False, c
            assert c["span_records"] == 0, c

    def test_disabled_span_helper_cost_is_noise(self):
        """The ≤1% step-time budget, pinned structurally: a disabled
        ``obs.span`` is one cached None check returning a shared
        nullcontext. Bound its per-call cost at 5 µs — the PR-3 bench's
        steps run ~20 ms, so even a span per step, per save, and per
        feed get stays orders of magnitude under 1%."""
        import time as _time

        from pytorch_operator_tpu import obs

        assert not obs.trace_enabled()
        before = obs.records_emitted()
        n = 50_000
        t0 = _time.perf_counter()
        for _ in range(n):
            with obs.span("step", cat="step"):
                pass
        per_call = (_time.perf_counter() - t0) / n
        assert per_call < 5e-6, f"disabled span helper costs {per_call:.2e}s"
        assert obs.records_emitted() == before

    def test_artifact_shape_is_committed_schema(self, smoke_result, tmp_path):
        out = tmp_path / "bench.json"
        dataplane_bench.run(
            steps=6, checkpoint_every=3, dim=64, batch=32,
            out=str(out), work_dir=str(tmp_path), log=lambda *_: None,
        )
        data = json.loads(out.read_text())
        assert data["bench"] == "data_plane"
        comp = data["comparisons"]
        for field in (
            "ckpt_stall_p50_reduction",
            "ckpt_stall_p99_reduction",
            "steps_per_sec_speedup_async",
            "prefetched_step_thread_puts",
            "async_saves_verified",
        ):
            assert field in comp
        assert comp["async_saves_verified"] is True
