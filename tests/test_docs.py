"""Docs-drift guard: user-facing docs must reference real code.

MIGRATION.md and README.md are the user-switch surface — every
backticked repo path or ``pytorch_operator_tpu.*`` module they name must
exist, or the docs rot silently as code moves (the same cannot-drift
principle the CRD generator applies to the API schema).
"""

import re
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parents[1]
PKG = REPO / "pytorch_operator_tpu"

# Upstream-reference paths that legitimately do not exist in this tree
# (they describe the Kubeflow operator being migrated FROM).
UPSTREAM = {
    "examples/smoke-dist/dist_sendrecv.py",
    "pkg/apis/pytorch/v1/types.go",
}

PATH_RE = re.compile(r"^[A-Za-z0-9_][A-Za-z0-9_/.\-*]*\.(py|md|yaml|yml|json|cc)$")


def _backtick_spans(text: str):
    return re.findall(r"`([^`\n]+)`", text)


def _module_refs(text: str):
    """Dotted modules appearing anywhere (incl. inside command lines)."""
    return set(re.findall(r"pytorch_operator_tpu(?:\.[A-Za-z0-9_]+)+", text))


def _resolves(path_str: str) -> bool:
    for base in (REPO, PKG):
        if "*" in path_str:
            if list(base.glob(path_str)):
                return True
        elif (base / path_str).exists():
            return True
    return False


@pytest.mark.parametrize("doc", ["MIGRATION.md", "README.md"])
def test_doc_paths_exist(doc):
    text = (REPO / doc).read_text()
    missing = []
    for span in _backtick_spans(text):
        span = span.strip()
        if span in UPSTREAM or not PATH_RE.match(span):
            continue
        if not _resolves(span):
            missing.append(span)
    assert missing == [], f"{doc} references nonexistent paths: {missing}"


@pytest.mark.parametrize("doc", ["MIGRATION.md", "README.md"])
def test_doc_modules_importable(doc):
    text = (REPO / doc).read_text()
    missing = []
    for mod in sorted(_module_refs(text)):
        # Resolve as a file path (no import: docs may name workload
        # modules whose import costs a jax load).
        rel = Path(*mod.split(".")[1:])
        if not (
            (PKG / rel).with_suffix(".py").exists()
            or (PKG / rel / "__init__.py").exists()
        ):
            missing.append(mod)
    assert missing == [], f"{doc} references nonexistent modules: {missing}"
