"""Multi-host async-save dryrun (checkpoint/multihost.py): the
primary-host commit protocol and its per-process writer barriers,
exercised with REAL processes sharing a checkpoint directory — no TPUs,
no mocks, the exact file rendezvous a pod would run.

Invariants pinned here:

- a step is sidecar-verified ONLY after every process's shard is
  durable (the primary's ``wait_all`` precedes the sidecar);
- a process that never arrives fails the save on every survivor
  (recorded + reported, never raised into the step loop) and the step
  never verifies — restore falls back to the last verified step;
- barriers compose with the async writer's ordering: per-process
  pipelined submits still commit 1, 2, 3... with one sidecar each;
- marker GC: the rendezvous files do not accumulate across steps.
"""

from __future__ import annotations

import json
import multiprocessing as mp
import time
from pathlib import Path

import pytest

from pytorch_operator_tpu.checkpoint import integrity
from pytorch_operator_tpu.checkpoint.async_writer import AsyncCheckpointWriter
from pytorch_operator_tpu.checkpoint.multihost import (
    BARRIER_DIR,
    BarrierTimeout,
    CommitBarrier,
    make_multihost_commit,
)

pytestmark = pytest.mark.chaos


# ---- barrier units ----


class TestCommitBarrier:
    def test_wait_all_returns_once_everyone_arrives(self, tmp_path):
        b0 = CommitBarrier(tmp_path, 0, 2)
        b1 = CommitBarrier(tmp_path, 1, 2)
        b0.arrive("written", 3)
        with pytest.raises(BarrierTimeout):
            b0.wait_all("written", 3, timeout=0.2)
        b1.arrive("written", 3)
        b0.wait_all("written", 3, timeout=2.0)  # no raise
        b1.wait_all("written", 3, timeout=2.0)

    def test_timeout_names_the_missing_processes(self, tmp_path):
        b0 = CommitBarrier(tmp_path, 0, 3)
        b0.arrive("written", 1)
        with pytest.raises(BarrierTimeout, match=r"\[1, 2\]"):
            b0.wait_all("written", 1, timeout=0.2)

    def test_arrive_is_idempotent_and_atomic(self, tmp_path):
        b = CommitBarrier(tmp_path, 0, 1)
        b.arrive("written", 7)
        b.arrive("written", 7)
        markers = list((tmp_path / BARRIER_DIR).iterdir())
        assert [m.name for m in markers] == ["written-7.p0"]

    def test_targeted_wait(self, tmp_path):
        b0 = CommitBarrier(tmp_path, 0, 3)
        b1 = CommitBarrier(tmp_path, 1, 3)
        b0.arrive("committed", 2)
        # Waiting only on the primary succeeds though 2 never arrived.
        b1.wait_all("committed", 2, timeout=1.0, procs=(0,))

    def test_out_of_world_process_id_rejected(self, tmp_path):
        with pytest.raises(ValueError):
            CommitBarrier(tmp_path, 3, 3)


# ---- in-process protocol (writers in threads, shared dir) ----


def _mk_writer(root: Path, pid: int, n: int, timeout: float = 10.0):
    def write_shard(step, payload, fault):
        d = root / str(step)
        d.mkdir(parents=True, exist_ok=True)
        (d / f"shard-{pid}.json").write_text(json.dumps({"p": pid}))

    commit = make_multihost_commit(
        root,
        write_shard,
        process_id=pid,
        num_processes=n,
        barrier_timeout=timeout,
        on_abort=lambda s: (root / str(s) / f"shard-{pid}.json").unlink(
            missing_ok=True
        ),
    )
    # Only the primary's writer owns the shared fence, and a failed
    # barrier must LEAVE it standing (peer shards the primary cannot
    # see may exist — fenced, not torn).
    return AsyncCheckpointWriter(
        commit,
        root=root if pid == 0 else None,
        clear_fence_on_error=False,
    )


class TestMultihostProtocol:
    def test_all_shards_present_before_verify(self, tmp_path):
        N = 3
        writers = [_mk_writer(tmp_path, p, N) for p in range(N)]
        for s in (1, 2, 3):
            for w in writers:
                w.submit(s, None)
        for w in writers:
            assert w.close() is True
        for w in writers:
            assert not w.errors, w.errors
            assert w.committed == [1, 2, 3]  # ordered per process
        for s in (1, 2, 3):
            assert integrity.verify_step(tmp_path, s) is True
            shards = sorted(p.name for p in (tmp_path / str(s)).glob("*"))
            assert shards == [f"shard-{p}.json" for p in range(N)]

    def test_markers_are_garbage_collected(self, tmp_path):
        N = 2
        writers = [_mk_writer(tmp_path, p, N) for p in range(N)]
        for s in range(1, 6):
            for w in writers:
                w.submit(s, None)
        for w in writers:
            w.close()
        leftover = sorted(
            p.name for p in (tmp_path / BARRIER_DIR).iterdir()
        )
        # Only the NEWEST step's committed marker may remain (its
        # consumers are gone; the next commit would sweep it).
        assert leftover == ["committed-5.p0"], leftover

    def test_dead_peer_fails_save_and_step_never_verifies(self, tmp_path):
        """The crash-window invariant: a secondary that never writes its
        shard times out the primary's barrier — the save FAILS (recorded,
        loop survives) and no sidecar ever lands, so restore falls back."""
        # A 2-process world where process 1 simply never runs.
        w0 = _mk_writer(tmp_path, 0, 2, timeout=0.5)
        w0.submit(9, None)
        w0.close()
        assert [s for s, _ in w0.errors] == [9]
        assert isinstance(w0.errors[0][1], BarrierTimeout)
        # Fenced, not torn: the step stays behind its inflight fence
        # (verify False, never "unknown-accepted"), so the verified
        # scan skips it entirely.
        assert integrity.verify_step(tmp_path, 9) is False
        # The aborting process cleaned its shard: no bytes masquerade.
        assert not (tmp_path / "9" / "shard-0.json").exists()
        assert integrity.latest_verified_step(tmp_path) is None

    def test_later_saves_proceed_after_a_failed_barrier(self, tmp_path):
        """A lost rendezvous must not poison the writer: the next save
        (with the peer back) commits and verifies."""
        N = 2
        w0 = _mk_writer(tmp_path, 0, N, timeout=0.6)
        w1 = _mk_writer(tmp_path, 1, N, timeout=10.0)
        w0.submit(1, None)  # peer absent for step 1: fails on w0
        w0.wait()
        assert [s for s, _ in w0.errors] == [1]
        # Step 2: both participate. (w1 never saw step 1 — its first
        # submit is step 2, and the protocol does not require aligned
        # histories, only aligned rendezvous per step.)
        w0.submit(2, None)
        w1.submit(2, None)
        assert w0.close() is True
        assert w1.close() is True
        assert integrity.latest_verified_step(tmp_path) == 2


# ---- real multi-process dryrun ----


def _proc_main(root: str, pid: int, n: int, steps: int, die_at):
    """One 'host' of the dryrun world: pipelined async submits through
    the shared-barrier commit. ``die_at=(step, pid)`` kills THIS process
    mid-protocol (before its shard write) to model a crashed host."""
    root = Path(root)

    def write_shard(step, payload, fault):
        if die_at is not None and die_at == [step, pid]:
            import os

            os._exit(137)  # SIGKILL analog: no cleanup, no barrier exit
        d = root / str(step)
        d.mkdir(parents=True, exist_ok=True)
        (d / f"shard-{pid}.json").write_text(json.dumps({"p": pid}))

    commit = make_multihost_commit(
        root, write_shard, process_id=pid, num_processes=n,
        barrier_timeout=5.0,
        on_abort=lambda s: (root / str(s) / f"shard-{pid}.json").unlink(
            missing_ok=True
        ),
    )
    w = AsyncCheckpointWriter(
        commit,
        root=root if pid == 0 else None,
        clear_fence_on_error=False,
    )
    for s in range(1, steps + 1):
        w.submit(s, None)
    w.close()
    # Report what this process saw on its own status line.
    (root / f"result-{pid}.json").write_text(
        json.dumps(
            {
                "committed": w.committed,
                "errors": [s for s, _ in w.errors],
            }
        )
    )


def _spawn_world(root: Path, n: int, steps: int, die_at=None):
    ctx = mp.get_context("spawn")  # clean interpreters: the real shape
    procs = [
        ctx.Process(
            target=_proc_main,
            args=(str(root), pid, n, steps, die_at),
        )
        for pid in range(n)
    ]
    for p in procs:
        p.start()
    deadline = time.monotonic() + 60
    for p in procs:
        p.join(max(deadline - time.monotonic(), 1))
    return procs


def test_multiprocess_dryrun_commits_and_verifies(tmp_path):
    """The acceptance dryrun: 3 real processes, 3 pipelined saves each,
    every step ends with all shards present and sidecar-verified."""
    procs = _spawn_world(tmp_path, n=3, steps=3)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]
    for pid in range(3):
        res = json.loads((tmp_path / f"result-{pid}.json").read_text())
        assert res["committed"] == [1, 2, 3]
        assert res["errors"] == []
    for s in (1, 2, 3):
        assert integrity.verify_step(tmp_path, s) is True
        assert len(list((tmp_path / str(s)).glob("shard-*.json"))) == 3


def test_multiprocess_dryrun_killed_host_fences_the_step(tmp_path):
    """Kill host 2 before its step-2 shard write: step 1 stays
    verified, step 2 never verifies (every survivor's barrier fails and
    reports), and recovery falls back to step 1."""
    procs = _spawn_world(tmp_path, n=3, steps=3, die_at=[2, 2])
    assert procs[2].exitcode == 137
    res0 = json.loads((tmp_path / "result-0.json").read_text())
    assert res0["committed"] == [1]
    assert 2 in res0["errors"]
    assert integrity.verify_step(tmp_path, 1) is True
    assert integrity.verify_step(tmp_path, 2) is not True
    assert integrity.latest_verified_step(tmp_path) == 1
