"""Concurrency stress tests for the supervisor.

Reference analog: the operator's goroutine-heavy informer/workqueue code is
CI-tested with ``go test -race`` (SURVEY.md §4/§5 "Race detection"). Python
has no race detector, so this is the translation: hammer one Supervisor
from several threads (submit / reconcile / scale / delete / metrics render)
against the FakeRunner and assert the invariants that data races would
break — no lost jobs, no duplicate replica spawns, counters consistent,
store files parseable.
"""

from __future__ import annotations

import threading
import time

from pytorch_operator_tpu.api.types import ElasticPolicy
from pytorch_operator_tpu.controller.runner import FakeRunner, ReplicaPhase
from pytorch_operator_tpu.controller.supervisor import Supervisor

from tests.testutil import new_job

import pytest




class TestSupervisorStress:
    def test_concurrent_submit_sync_delete(self, tmp_path):
        """Many submitters + a reconciler + a deleter + a metrics reader,
        one store. Invariant: every job either reaches a terminal state or
        is cleanly deleted; nothing is lost or double-counted."""
        sup = Supervisor(state_dir=tmp_path, runner=FakeRunner(), persist=True)
        n_jobs = 24
        submitted = []
        deleted = set()
        submit_lock = threading.Lock()
        stop = threading.Event()
        errors = []

        def guard(fn):
            def run():
                try:
                    fn()
                except Exception as e:  # noqa: BLE001 - surface in main thread
                    errors.append(e)
                    stop.set()

            return run

        def submitter(base):
            def go():
                for i in range(n_jobs // 2):
                    key = sup.submit(new_job(name=f"stress-{base}-{i}", workers=1))
                    with submit_lock:
                        submitted.append(key)

            return go

        def reconciler():
            while not stop.is_set():
                sup.sync_once()
                time.sleep(0.001)  # yield: single-core box, avoid starving peers

        def metrics_reader():
            while not stop.is_set():
                sup.metrics.render_text()
                time.sleep(0.001)

        def deleter():
            # Tear down every 6th job mid-flight: exercises the
            # delete-vs-sync interleaving the per-key lock serializes.
            victims = 0
            while not stop.is_set() and victims < n_jobs // 6:
                with submit_lock:
                    candidates = [k for k in submitted if k not in deleted]
                if len(candidates) > victims:
                    key = candidates[victims]
                    if sup.delete_job(key):
                        deleted.add(key)
                        victims += 1
                time.sleep(0.002)

        threads = [
            threading.Thread(target=guard(submitter("a"))),
            threading.Thread(target=guard(submitter("b"))),
            threading.Thread(target=guard(reconciler)),
            threading.Thread(target=guard(metrics_reader)),
            threading.Thread(target=guard(deleter)),
        ]
        for t in threads:
            t.start()
        threads[0].join(timeout=60)
        threads[1].join(timeout=60)
        # Drive every submitted job to completion: FakeRunner replicas stay
        # Pending until a state is set, so flip them to succeeded as syncs
        # spawn them.
        deadline = time.time() + 45
        while time.time() < deadline:
            for h in list(sup.runner.handles.values()):
                if h.phase == ReplicaPhase.PENDING:
                    sup.runner.set_phase(h.name, ReplicaPhase.SUCCEEDED, exit_code=0)
            sup.sync_once()
            if all(
                (j := sup.get(k)) is None or j.is_finished() for k in submitted
            ):
                break
        stop.set()
        for t in threads[2:]:
            t.join(timeout=30)
        assert not errors, errors

        assert len(submitted) == n_jobs
        # Every job either finished or was cleanly deleted; none lost/stuck.
        finished = [k for k in submitted if (j := sup.get(k)) and j.is_finished()]
        gone = [k for k in submitted if sup.get(k) is None]
        assert len(finished) + len(gone) == n_jobs
        assert set(gone) == deleted
        # Counter consistency: jobs_created increments on a job's FIRST
        # reconcile (the Created condition), so only mid-flight deletions —
        # which can vanish before ever being synced — may be missing, and
        # nothing is ever double-counted.
        assert n_jobs - len(deleted) <= sup.metrics.jobs_created.get() <= n_jobs
        assert n_jobs - len(deleted) <= sup.metrics.jobs_succeeded.get() <= n_jobs

    def test_concurrent_scale_requests(self, tmp_path):
        """Racing scale calls must serialize into a valid final worker count
        and never produce a half-resized world."""
        sup = Supervisor(state_dir=tmp_path, runner=FakeRunner(), persist=False)
        key = sup.submit(
            new_job(
                name="scaly",
                workers=2,
                elastic=ElasticPolicy(min_replicas=1, max_replicas=4, max_restarts=10),
            )
        )
        sup.sync_once()
        errors = []

        def scaler(n):
            def go():
                try:
                    sup.scale(key, n)
                except Exception as e:  # noqa: BLE001
                    errors.append(e)

            return go

        threads = [threading.Thread(target=scaler(n)) for n in (1, 2, 3, 4, 3, 2)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errors, errors

        from pytorch_operator_tpu.api.types import ReplicaType

        job = sup.get(key)
        want = job.spec.replica_specs[ReplicaType.WORKER].replicas
        assert want in (1, 2, 3, 4)
        # Reconcile until the live world matches the final spec.
        for _ in range(200):
            sup.sync_once()
            for h in list(sup.runner.handles.values()):
                if h.phase == ReplicaPhase.PENDING:
                    sup.runner.set_phase(h.name, ReplicaPhase.RUNNING)
            workers = [
                h for h in sup.runner.list_for_job(key) if "worker" in h.name
            ]
            if len(workers) == want:
                break
        assert len(workers) == want


class TestSchedulingStress:
    def test_concurrent_apply_suspend_preempt_sync(self, tmp_path):
        """Hammer the new mutation paths together: appliers rewriting
        specs, suspend/resume flappers, a preempting reconciler pass, and
        a deleter — all against one supervisor. Invariants: no exception
        escapes a worker, every surviving job's store record parses, and
        no job ends up with MORE replicas than its current spec desires
        (the double-create class of race)."""
        sup = Supervisor(
            state_dir=tmp_path,
            runner=FakeRunner(capacity=16),
            persist=True,
            preempt=True,
        )
        n_jobs = 12
        for i in range(n_jobs):
            sup.submit(new_job(name=f"s{i}", workers=1))
        hi = new_job(name="vip", workers=2)
        hi.spec.run_policy.scheduling_policy.priority = 50
        sup.submit(hi)
        errors = []
        stop = threading.Event()

        def guard(fn):
            def run():
                try:
                    while not stop.is_set():
                        fn()
                except Exception as e:  # noqa: BLE001 — the test asserts none
                    errors.append(e)
            return run

        def syncer():
            sup.sync_once()

        def applier():
            # Disjoint from the deleter's target: apply resurrects a
            # deleted job (create-or-update), which would confuse the
            # final invariants.
            for i in range(0, n_jobs - 1, 3):
                updated = new_job(name=f"s{i}", workers=2)
                updated.spec.run_policy.backoff_limit = 7
                sup.apply(updated)
            time.sleep(0.002)

        def flapper():
            # The SUPPORTED cross-process path (marker + processor) — it
            # takes the per-key reconcile lock like the real CLI flow.
            for i in range(1, n_jobs - 1, 3):
                j = sup.get(f"default/s{i}")
                if j is None or j.is_finished():
                    continue
                sup.store.mark_suspend(f"default/s{i}", not j.spec.run_policy.suspend)
            sup.process_suspend_markers()
            time.sleep(0.002)

        def deleter():
            sup.delete_job(f"default/s{n_jobs - 1}")
            time.sleep(0.005)

        threads = [
            threading.Thread(target=guard(fn))
            for fn in (syncer, syncer, applier, flapper, deleter)
        ]
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=10)
            assert not t.is_alive(), "worker deadlocked (lock-ordering bug?)"
        assert not errors, errors

        # Invariants after the storm settles.
        sup.sync_once()
        for job in sup.list_jobs():
            key = f"{job.metadata.namespace}/{job.metadata.name}"
            desired = sum(
                rs.replicas or 0 for rs in job.spec.replica_specs.values()
            )
            live = [h for h in sup.runner.list_for_job(key) if h.is_active()]
            assert len(live) <= desired, (
                f"{key}: {len(live)} live replicas > desired {desired}"
            )
        # The store survived: a FRESH store (cold load from disk) must see
        # exactly the surviving jobs — a torn/corrupt record would be
        # silently skipped by the loader and show up as a missing key.
        from pytorch_operator_tpu.controller.store import JobStore

        fresh = JobStore(persist_dir=tmp_path / "jobs")
        live_keys = {
            f"{j.metadata.namespace}/{j.metadata.name}" for j in sup.list_jobs()
        }
        assert {
            f"{j.metadata.namespace}/{j.metadata.name}" for j in fresh.list()
        } == live_keys
