"""Convergence-regression guards: golden loss curves.

Round-2 requirement (VERDICT "Next round" #8): perf work must not be able
to silently corrupt training numerics. These short deterministic runs —
fixed seeds, fixed synthetic data, CPU backend — were measured bit-exact
across repeated runs on 2026-07-30; the tolerance band (rtol 2e-3)
absorbs minor XLA/jax-version drift while catching real numerics bugs
(wrong BN statistics, broken gradient paths, optimizer regressions).
A NaN/Inf anywhere fails outright.

If an INTENTIONAL numerics change (new init, different optimizer
defaults) moves the curves, re-record the goldens with the generator
documented in each test.
"""

from __future__ import annotations

import numpy as np

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.parallel import make_mesh

import pytest

# Fast-lane exclusion (-m 'not slow'): real training to convergence goldens.
pytestmark = pytest.mark.slow

# Golden curves, 6 steps each (generated 2026-07-30, jax 0.9.0 CPU,
# bit-exact over repeated runs).
RESNET18_GOLDEN = [2.494654, 2.425305, 0.967371, 0.889857, 0.903853, 0.876274]
LLAMA_TINY_GOLDEN = [6.020604, 5.786736, 5.556229, 5.33003, 5.108804, 4.892921]
RTOL = 2e-3


def _check(losses, golden, name):
    losses = np.asarray(losses)
    assert np.isfinite(losses).all(), f"{name} produced NaN/Inf: {losses}"
    np.testing.assert_allclose(
        losses,
        golden,
        rtol=RTOL,
        err_msg=(
            f"{name} loss curve drifted from the golden run — a numerics "
            "regression, or an intentional change that needs re-recording "
            "(see module docstring)"
        ),
    )
    assert losses[-1] < losses[0], f"{name} is not training"


class TestGoldenCurves:
    def test_resnet18_short_run_matches_golden(self):
        """ResNet-18, 32px, batch 8, SGD+momentum+BN, bf16 compute —
        the full resnet_bench train-step body (label smoothing, BN
        statistics updates) at miniature scale."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.models.resnet import ResNet18
        from pytorch_operator_tpu.parallel.data import global_batch
        from pytorch_operator_tpu.workloads.datasets import synthetic_images
        from pytorch_operator_tpu.workloads.resnet_bench import (
            _train_step_fn,
            build_train_state,
        )

        model = ResNet18(num_classes=10)
        mesh = make_mesh("dp=1", devices=jax.devices()[:1])
        params, stats, opt, tx = build_train_state(
            model, mesh, lr=0.1, momentum=0.9, seed=0, image_size=32
        )
        hx, hy = synthetic_images(8, 32, 32, 10)
        gx = global_batch(hx.astype(jnp.bfloat16), mesh)
        gy = global_batch(hy, mesh)
        step = jax.jit(_train_step_fn(model, tx))
        losses = []
        for _ in range(len(RESNET18_GOLDEN)):
            params, stats, opt, loss = step(params, stats, opt, gx, gy)
            losses.append(float(loss))
        _check(losses, RESNET18_GOLDEN, "resnet18")

    def test_llama_tiny_short_run_matches_golden(self):
        """llama_tiny + AdamW through the shared LM trainer (the same
        make_lm_train_step the flagship workload uses)."""
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_operator_tpu.models import llama as llama_lib
        from pytorch_operator_tpu.workloads.trainer import (
            init_sharded_train_state,
            make_lm_train_step,
        )

        cfg = llama_lib.llama_tiny(attn_impl="dense")
        tokens = jnp.asarray(
            np.random.default_rng(7).integers(0, 256, (8, 32)), jnp.int32
        )
        tx = optax.adamw(1e-3)
        mesh = make_mesh("dp=1", devices=jax.devices()[:1])
        model = llama_lib.Llama(cfg, mesh=mesh)
        state, _ = init_sharded_train_state(
            lambda k: model.init(k, np.zeros((1, 32), np.int32)), tx, mesh
        )
        step = make_lm_train_step(model, tx, mesh)
        losses = []
        for _ in range(len(LLAMA_TINY_GOLDEN)):
            state, loss = step(state, tokens)
            losses.append(float(jax.device_get(loss)))
        _check(losses, LLAMA_TINY_GOLDEN, "llama-tiny")
