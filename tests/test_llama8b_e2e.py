"""The REAL Llama-3-8B config, executed end-to-end (VERDICT r3 Missing
#1 / Next #2): full dims — d_model 4096, 32 scanned layers, 128k vocab,
chunked xent — trained for real steps on an fsdp=8 virtual-CPU mesh with
bf16 params + adafactor, then checkpoint-resumed through the production
resume path. Until this run, "sharding config validated" rested on
eval_shape arithmetic (tests/test_llama8b_plan.py — which stays as the
fast guard).

Scaled in DEPTH not dims: batch 8 x seq 32 = 256 tokens/step keeps the
CPU matmul time (~6N FLOPs/token on one host core) and the activation
footprint small enough that remat is deliberately OFF — at 256 tokens
activations are ~1 GiB while params+grads are ~32 GiB, so recompute
would double step time to save nothing that matters here.

Opt-in (TPUJOB_RUN_8B=1): one run takes tens of minutes and ~40+ GiB
RSS — it must not ride the regular suite. BASELINE.md records the
measured wall/RSS from the round-4 session.
"""

from __future__ import annotations

import os
import resource
import time

import numpy as np
import pytest

# 8 virtual devices time-slice ONE physical core here, so the slowest
# collective participant reaches its rendezvous ~7x later than the
# fastest; at 8B scale that spread exceeds XLA:CPU's default 40s
# termination timeout and the run is killed mid-AllGather (observed
# first-hand). Raise the stuck/terminate budgets — must land in
# XLA_FLAGS before the CPU client is created.
#
# ONLY under the opt-in: pytest imports every module at collection, so
# an unconditional mutation leaks these flags into the whole suite's
# process — and a jaxlib that doesn't know them fatally aborts
# (parse_flags_from_env F-check) at the first CPU client creation,
# taking every jax test down with it.
if os.environ.get("TPUJOB_RUN_8B"):
    _flags = os.environ.get("XLA_FLAGS", "")
    for _flag in (
        "--xla_cpu_collective_call_warn_stuck_timeout_seconds=3600",
        "--xla_cpu_collective_call_terminate_timeout_seconds=7200",
    ):
        if _flag.split("=")[0] not in _flags:
            _flags = f"{_flags} {_flag}".strip()
    os.environ["XLA_FLAGS"] = _flags

import tests.jaxenv  # noqa: F401,E402

pytestmark = pytest.mark.skipif(
    not os.environ.get("TPUJOB_RUN_8B"),
    reason="8B end-to-end is opt-in (TPUJOB_RUN_8B=1): ~1h, ~40+ GiB RSS",
)


def test_8b_full_config_trains_and_resumes(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path / "ckpt"))
    from pytorch_operator_tpu.workloads import llama_train

    common = dict(
        config="8b",
        mesh_spec="fsdp=8",
        batch_size=8,
        seq_len=32,
        warmup=1,
        optimizer="adafactor",
        param_dtype="bfloat16",
        remat=False,
        checkpoint_every=1,
    )

    # RSS budget (VERDICT r4 Weak #4): round 4 measured ~98 GiB peak on
    # this ~125 GiB host — ~20% headroom. Growth toward the ceiling must
    # fail LOUDLY here, not flake the host when some later session adds
    # one more resident allocation.
    RSS_BUDGET_GIB = 105.0

    def stamp(tag, t0):
        wall = time.time() - t0
        rss_gib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 2**20
        print(
            f"[8b-e2e] {tag}: wall {wall:.0f}s, peak RSS {rss_gib:.1f} GiB",
            flush=True,
        )
        assert rss_gib <= RSS_BUDGET_GIB, (
            f"peak RSS {rss_gib:.1f} GiB exceeds the documented "
            f"{RSS_BUDGET_GIB} GiB budget (round 4 baseline ~98 GiB); "
            "find the regression before it flakes the whole host"
        )

    # ---- life 1: two real train steps of the production graph ----
    logs1 = []
    t0 = time.time()
    r1 = llama_train.run(
        steps=2, max_steps=2,
        log=lambda m: (logs1.append(str(m)), print(m, flush=True)),
        **common,
    )
    stamp("life 1 (init + compile + 2 steps + 2 checkpoints)", t0)
    assert np.isfinite(r1["final_loss"]), r1
    # Fresh init on a 128k vocab: xent starts near ln(V) ~ 11.8.
    assert 5.0 < r1["final_loss"] < 15.0, r1
    assert r1["params_m"] == pytest.approx(8030, rel=0.05), r1  # ~8.03B
    ckpts = tmp_path / "ckpt"
    saved_steps = sorted(int(p.name) for p in ckpts.iterdir() if p.name.isdigit())
    assert saved_steps and saved_steps[-1] == 2, saved_steps

    # ---- life 2: the production resume path restores step 2's 16 GiB
    # sharded state onto a fresh fsdp=8 world and trains one more step.
    logs2 = []
    t0 = time.time()
    r2 = llama_train.run(
        steps=3, max_steps=3,
        log=lambda m: (logs2.append(str(m)), print(m, flush=True)),
        **common,
    )
    stamp("life 2 (restore + 1 step)", t0)
    assert np.isfinite(r2["final_loss"]), r2
    resumed = [ln for ln in logs2 if "resumed from checkpoint" in ln]
    assert resumed and "step 2" in resumed[0], logs2[:10]
