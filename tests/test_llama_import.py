"""HF/PyTorch → flax Llama weight import (models/llama_import.py).

The gold test builds a random HF-layout torch state_dict, runs a REAL
torch reference implementation of the architecture (RMSNorm, rotate-half
RoPE, GQA attention, SwiGLU — mirroring HF modeling_llama semantics),
imports the same weights into the flax model, and asserts the logits
match. That pins every transpose/reshape/stack in the importer AND the
architectural equivalence of the two implementations.
"""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import llama as llama_lib
from pytorch_operator_tpu.models.llama_import import (
    export_hf_llama_state_dict,
    import_hf_llama_state_dict,
)

torch = pytest.importorskip("torch")


def _cfg():
    return llama_lib.llama_tiny(
        vocab_size=64,
        d_model=32,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        head_dim=8,
        d_ff=48,
    )


def _random_state_dict(cfg, seed=0):
    g = torch.Generator().manual_seed(seed)

    def w(*shape):
        return torch.randn(*shape, generator=g) * 0.1

    sd = {
        "model.embed_tokens.weight": w(cfg.vocab_size, cfg.d_model),
        "model.norm.weight": 1.0 + 0.1 * w(cfg.d_model),
        "lm_head.weight": w(cfg.vocab_size, cfg.d_model),
    }
    H, K, hd, D, F = (
        cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_model, cfg.d_ff,
    )
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        sd[p + "input_layernorm.weight"] = 1.0 + 0.1 * w(D)
        sd[p + "post_attention_layernorm.weight"] = 1.0 + 0.1 * w(D)
        sd[p + "self_attn.q_proj.weight"] = w(H * hd, D)
        sd[p + "self_attn.k_proj.weight"] = w(K * hd, D)
        sd[p + "self_attn.v_proj.weight"] = w(K * hd, D)
        sd[p + "self_attn.o_proj.weight"] = w(D, H * hd)
        sd[p + "mlp.gate_proj.weight"] = w(F, D)
        sd[p + "mlp.up_proj.weight"] = w(F, D)
        sd[p + "mlp.down_proj.weight"] = w(D, F)
    return sd


def _torch_reference_forward(sd, cfg, tokens: np.ndarray) -> np.ndarray:
    """Minimal torch Llama forward mirroring HF semantics (f32)."""
    B, S = tokens.shape
    H, K, hd, D = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim, cfg.d_model
    t = torch.from_numpy(tokens.astype(np.int64))

    def rms(x, wname):
        v = x.pow(2).mean(-1, keepdim=True)
        return x * torch.rsqrt(v + cfg.rms_eps) * sd[wname]

    def rope(x):  # [B, S, h, hd], rotate-half convention
        half = hd // 2
        freqs = cfg.rope_theta ** (
            -torch.arange(0, half, dtype=torch.float32) / half
        )
        ang = torch.arange(S, dtype=torch.float32)[:, None] * freqs[None, :]
        cos = torch.cos(ang)[None, :, None, :]
        sin = torch.sin(ang)[None, :, None, :]
        x1, x2 = x[..., :half], x[..., half:]
        return torch.cat([x1 * cos - x2 * sin, x2 * cos + x1 * sin], dim=-1)

    x = sd["model.embed_tokens.weight"][t]
    mask = torch.tril(torch.ones(S, S, dtype=torch.bool))
    for i in range(cfg.n_layers):
        p = f"model.layers.{i}."
        y = rms(x, p + "input_layernorm.weight")
        q = (y @ sd[p + "self_attn.q_proj.weight"].T).view(B, S, H, hd)
        k = (y @ sd[p + "self_attn.k_proj.weight"].T).view(B, S, K, hd)
        v = (y @ sd[p + "self_attn.v_proj.weight"].T).view(B, S, K, hd)
        q, k = rope(q), rope(k)
        G = H // K
        qg = q.view(B, S, K, G, hd)
        scores = torch.einsum("bskgd,btkd->bkgst", qg, k) / (hd ** 0.5)
        scores = scores.masked_fill(~mask, float("-inf"))
        probs = torch.softmax(scores, dim=-1)
        out = torch.einsum("bkgst,btkd->bskgd", probs, v).reshape(B, S, H * hd)
        x = x + out @ sd[p + "self_attn.o_proj.weight"].T
        y = rms(x, p + "post_attention_layernorm.weight")
        h = torch.nn.functional.silu(y @ sd[p + "mlp.gate_proj.weight"].T) * (
            y @ sd[p + "mlp.up_proj.weight"].T
        )
        x = x + h @ sd[p + "mlp.down_proj.weight"].T
    x = rms(x, "model.norm.weight")
    return (x @ sd["lm_head.weight"].T).numpy()


class TestLlamaImport:
    def test_logits_match_torch_reference(self):
        import jax

        cfg = _cfg()
        sd = _random_state_dict(cfg)
        params = import_hf_llama_state_dict(sd, cfg)
        tokens = np.random.default_rng(2).integers(
            0, cfg.vocab_size, (2, 12)
        ).astype(np.int32)

        ref = _torch_reference_forward(sd, cfg, tokens)
        model = llama_lib.Llama(cfg)
        ours = np.asarray(model.apply({"params": params}, tokens))
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_generation_runs_with_imported_weights(self):
        import dataclasses

        import jax

        from pytorch_operator_tpu.workloads.generate import (
            init_cache,
            make_generate,
        )

        cfg = _cfg()
        params = import_hf_llama_state_dict(_random_state_dict(cfg), cfg)
        dcfg = dataclasses.replace(cfg, decode=True, max_decode_len=24)
        model = llama_lib.Llama(dcfg)
        prompt = np.random.default_rng(3).integers(0, 64, (1, 8)).astype(np.int32)
        gen = make_generate(model, max_new_tokens=8)
        toks, _ = gen(
            params, init_cache(model, 1, 8), prompt, jax.random.key(0)
        )
        assert toks.shape == (1, 8)

    @pytest.mark.slow
    def test_imported_weights_quantize_and_decode_int8(self):
        """The serving path end to end: a real (HF-layout) checkpoint
        imports, quantizes to int8 (the importer's tree uses the same
        param vocabulary the contraction-axis rule keys on), and
        decodes through the quantize-mode model bit-identically to the
        eagerly-dequantized control."""
        import dataclasses

        import jax

        from pytorch_operator_tpu.ops.quantize import (
            QuantizedTensor,
            dequantize_tree,
            quantize_tree,
        )
        from pytorch_operator_tpu.workloads.generate import (
            init_cache,
            make_generate,
        )

        cfg = _cfg()
        params = import_hf_llama_state_dict(_random_state_dict(cfg), cfg)
        qparams = quantize_tree(params)
        assert isinstance(
            qparams["layers"]["attn"]["q_proj"]["kernel"], QuantizedTensor
        )
        dcfg = dataclasses.replace(
            cfg, decode=True, max_decode_len=24, quantize="int8"
        )
        model = llama_lib.Llama(dcfg)
        prompt = np.random.default_rng(3).integers(0, 64, (1, 8)).astype(np.int32)
        gen = make_generate(model, max_new_tokens=8)
        t_q, _ = gen(
            qparams, init_cache(model, 1, 8), prompt, jax.random.key(0)
        )
        t_e, _ = gen(
            dequantize_tree(qparams),
            init_cache(model, 1, 8),
            prompt,
            jax.random.key(0),
        )
        np.testing.assert_array_equal(np.asarray(t_q), np.asarray(t_e))

    def test_bf16_tensors_and_tied_embeddings(self):
        """Real checkpoints ship bf16 and may tie lm_head to the
        embedding table — both must import."""
        cfg = _cfg()
        sd = {k: v.to(torch.bfloat16) for k, v in _random_state_dict(cfg).items()}
        del sd["lm_head.weight"]  # tie_word_embeddings=true layout
        params = import_hf_llama_state_dict(sd, cfg)
        np.testing.assert_allclose(
            params["lm_head"]["kernel"],
            params["embed"]["embedding"].T,
        )

    def test_export_round_trips_exactly(self):
        """import(export(params)) == params, and export reproduces the
        original state_dict tensors — both directions are lossless."""
        import jax

        cfg = _cfg()
        sd = _random_state_dict(cfg)
        params = import_hf_llama_state_dict(sd, cfg)
        sd2 = export_hf_llama_state_dict(params, cfg)
        assert set(sd2) == set(sd)
        for k in sd:
            np.testing.assert_allclose(
                sd2[k], sd[k].numpy(), rtol=0, atol=0, err_msg=k
            )
        params2 = import_hf_llama_state_dict(sd2, cfg)
        for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(params2)):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_export_trained_flax_params(self):
        """Params born in THIS framework (flax init, boxed metadata)
        export to a state_dict the torch reference can run."""
        import flax.linen as nn
        import jax

        cfg = _cfg()
        model = llama_lib.Llama(cfg)
        variables = model.init(jax.random.key(5), np.zeros((1, 8), np.int32))
        sd = export_hf_llama_state_dict(variables["params"], cfg)  # boxed ok
        tokens = np.random.default_rng(6).integers(0, 64, (2, 8)).astype(np.int32)
        ref = _torch_reference_forward(
            {k: torch.from_numpy(v) for k, v in sd.items()}, cfg, tokens
        )
        ours = np.asarray(
            model.apply({"params": nn.meta.unbox(variables["params"])}, tokens)
        )
        np.testing.assert_allclose(ours, ref, rtol=2e-4, atol=2e-4)

    def test_moe_config_rejected_up_front(self):
        cfg = llama_lib.llama_tiny(n_experts=4)
        with pytest.raises(NotImplementedError, match="MoE"):
            import_hf_llama_state_dict({}, cfg)

    def test_shape_mismatch_rejected(self):
        cfg = _cfg()
        sd = _random_state_dict(cfg)
        sd["model.embed_tokens.weight"] = sd["model.embed_tokens.weight"][:, :16]
        with pytest.raises(ValueError, match="expected shape"):
            import_hf_llama_state_dict(sd, cfg)

    def test_missing_key_rejected(self):
        cfg = _cfg()
        sd = _random_state_dict(cfg)
        del sd["model.layers.1.mlp.up_proj.weight"]
        with pytest.raises(KeyError, match="up_proj"):
            import_hf_llama_state_dict(sd, cfg)
