"""Chaos scenario: ``fail_engine_step`` through a full spool round trip.

ROADMAP open item, scripted end-to-end: a REAL serve job (subprocess,
jax on CPU) runs under ``tpujob chaos`` with a ``fail_engine_step``
fault riding in via the env-threaded plan. A client drives the file
spool exactly like ``tpujob serve-request`` while the engine takes the
injected iteration fault mid-service. The contract under test is the
serve loop's failure-path hardening at the SERVICE boundary:

- the faulted iteration's in-flight requests get an error response
  (nobody blocks a timeout on a reply nothing will write),
- every submitted request gets EXACTLY ONE response,
- the engine keeps serving — later requests complete normally,
- no claims are stranded in the spool, and the job itself finishes
  Succeeded with zero restarts (an engine fault is not a crash).
"""

from __future__ import annotations

import threading

import pytest

from pytorch_operator_tpu.serving import Spool

pytestmark = [pytest.mark.chaos, pytest.mark.slow]


SERVE_JOB = """\
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: chaos-serve
spec:
  replica_specs:
    Master:
      replicas: 1
      restart_policy: OnFailure
      template:
        module: pytorch_operator_tpu.workloads.serve
        args: ["--spool", "{spool}", "--config", "tiny", "--slots", "2",
               "--chunk", "16", "--block", "4", "--max-decode-len", "128",
               "--max-requests", "3", "--idle-timeout", "120",
               "--report-every", "1"]
  run_policy:
    backoff_limit: 2
"""

SERVE_PLAN = """\
seed: 11
faults:
  - {kind: fail_engine_step, nth: 2}
"""


def test_fail_engine_step_full_spool_round_trip(tmp_path):
    from pytorch_operator_tpu.client import cli

    spool_dir = tmp_path / "spool"
    state = tmp_path / "state"
    job = tmp_path / "serve.yaml"
    job.write_text(SERVE_JOB.format(spool=spool_dir))
    plan = tmp_path / "plan.yaml"
    plan.write_text(SERVE_PLAN)

    result = {}

    def run_chaos():
        result["rc"] = cli.main(
            [
                "--state-dir", str(state),
                "chaos", str(job),
                "--plan", str(plan),
                "--timeout", "600",
            ]
        )

    supervisor = threading.Thread(target=run_chaos)
    supervisor.start()
    try:
        # Client half of the service: keep submitting until THREE
        # requests completed successfully (--max-requests 3 then ends
        # the serve job). The injected fault costs some in-flight
        # request an error response along the way; the client retries —
        # exactly what a production spool client does.
        spool = Spool(spool_dir)
        responses = []
        successes = 0
        for _ in range(12):  # 3 successes + fault casualties, bounded
            # 16 tokens at block=4 → each request spans several engine
            # iterations, so the nth=2 fault always catches a request
            # IN FLIGHT (a one-block request would finish inside its
            # admission step and the fault would abort an empty batch).
            rid = spool.submit(prompt_len=6, max_new_tokens=16)
            resp = spool.wait_response(rid, timeout=420)
            assert resp["id"] == rid
            responses.append((rid, resp))
            if "error" not in resp:
                successes += 1
                assert len(resp["tokens"]) >= 1
                assert resp["ttft_ms"] >= 0
            if successes >= 3:
                break
        assert successes == 3, responses
    finally:
        supervisor.join(timeout=600)
    assert not supervisor.is_alive(), "chaos run did not finish"
    assert result["rc"] == 0

    # Exactly-once: one response file per submitted request, none extra.
    ids = [rid for rid, _ in responses]
    assert len(set(ids)) == len(ids)
    response_files = {p.stem for p in (spool_dir / "responses").glob("*.json")}
    assert response_files == set(ids)
    # The injected fault surfaced as an error response on some request.
    errors = [r for _, r in responses if "error" in r]
    assert len(errors) == 1, responses
    assert "engine fault" in errors[0]["error"]
    # Recovery: a SUCCESSFUL response arrived after the faulted one —
    # the engine kept serving through the casualty.
    error_idx = next(i for i, (_, r) in enumerate(responses) if "error" in r)
    assert any("error" not in r for _, r in responses[error_idx + 1 :])
    # No stranded claims: the engine finished its drain cleanly.
    assert list((spool_dir / "claimed").glob("*.json")) == []
    assert list((spool_dir / "requests").glob("*.json")) == []

    # The supervisor saw a healthy job end-to-end: Succeeded, zero
    # restarts (the fault was absorbed by the serve loop, not a crash),
    # and the failure forensics are in the replica log.
    log = next((state / "logs").glob("*chaos-serve*master-0.log")).read_text()
    assert "engine step fault" in log
    assert "aborted" in log
