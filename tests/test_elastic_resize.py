"""Fast-lane units for the elastic resize machinery — no subprocesses.

- classify_death: the partial-gang vs whole-world decision table
  (coordinator death, below-min_replicas, no live master, resizable
  worker deaths);
- reassign_ranks: contiguous dense ranks over sparse survivor indices,
  zero duplicates, master pinned to 0;
- the resize record: atomic write/read/clear roundtrip, corrupt and
  missing records read as None;
- poll_resize fencing: a stale-generation process adopts its place in
  the new world or is evicted; a current-generation process sees
  nothing;
- the world_resize_thrash rule: fires on >= K resize transitions in one
  window (citing the triggering death events), stays quiet below the
  bar, and honors spec.observability.alerts threshold overrides;
- preempt_replica / kill_storm fault kinds: plan validation, injector
  due/consumption semantics, and chaos --record reconstruction (143
  exits -> preempt_replica; clustered SIGKILLs -> one kill_storm).
"""

from __future__ import annotations

import json

from pytorch_operator_tpu.api.types import ElasticPolicy, ReplicaType
from pytorch_operator_tpu.controller.elastic import (
    RESIZE,
    RESTART,
    build_resize_record,
    classify_death,
    clear_resize_record,
    member_id,
    read_resize_record,
    reassign_ranks,
    resize_record_path,
    write_resize_record,
)
from pytorch_operator_tpu.faults import Fault, FaultInjector, FaultPlan
from pytorch_operator_tpu.obs import rules as obs_rules
from pytorch_operator_tpu.runtime import rendezvous


class _H:
    """ReplicaHandle-shaped stub for the pure classifier."""

    def __init__(self, rtype, index, active=True):
        self.replica_type = rtype
        self.index = index
        self.name = f"{rtype.value.lower()}-{index}"
        self._active = active

    def is_active(self):
        return self._active


def _gang(workers=3, master_active=True):
    handles = [_H(ReplicaType.MASTER, 0, active=master_active)]
    handles += [_H(ReplicaType.WORKER, i) for i in range(workers)]
    return handles


class TestClassifyDeath:
    def test_worker_death_with_enough_survivors_resizes(self):
        handles = _gang(workers=3)
        dead = [handles[2]]  # worker-1
        d = classify_death(ElasticPolicy(1, 3, 4), handles, dead)
        assert d.action == RESIZE
        assert d.survivors == [0, 2]
        assert d.dead_workers == [1]

    def test_master_death_restarts_world(self):
        handles = _gang(workers=3)
        d = classify_death(ElasticPolicy(1, 3, 4), handles, [handles[0]])
        assert d.action == RESTART
        assert "coordinator" in d.reason.lower()

    def test_below_min_replicas_restarts_world(self):
        handles = _gang(workers=2)
        d = classify_death(ElasticPolicy(2, 2, 4), handles, [handles[1]])
        assert d.action == RESTART
        assert "min_replicas=2" in d.reason

    def test_no_live_master_restarts_world(self):
        handles = _gang(workers=2, master_active=False)
        d = classify_death(ElasticPolicy(1, 2, 4), handles, [handles[1]])
        assert d.action == RESTART

    def test_storm_of_deaths_classified_as_one_batch(self):
        # Three of four workers die in one pass: survivors 1 >= min 1
        # resizes; with min 2 the SAME batch restarts — the window is
        # the pass, not per-death.
        handles = _gang(workers=4)
        dead = [handles[1], handles[2], handles[4]]  # workers 0, 1, 3
        d = classify_death(ElasticPolicy(1, 4, 5), handles, dead)
        assert d.action == RESIZE
        assert d.survivors == [2]
        d = classify_death(ElasticPolicy(2, 4, 5), handles, dead)
        assert d.action == RESTART


class TestReassignRanks:
    def test_sparse_survivors_get_dense_ranks(self):
        ranks = reassign_ranks([4, 0, 2])
        assert ranks == {
            "master-0": 0,
            "worker-0": 1,
            "worker-2": 2,
            "worker-4": 3,
        }

    def test_no_duplicate_ranks_and_dense(self):
        ranks = reassign_ranks([7, 1, 3, 5])
        vals = sorted(ranks.values())
        assert vals == list(range(len(ranks)))

    def test_member_id_shape(self):
        assert member_id("Worker", 2) == "worker-2"
        assert member_id(ReplicaType.MASTER.value, 0) == "master-0"


class TestResizeRecord:
    def test_roundtrip_and_clear(self, tmp_path):
        rec = build_resize_record(
            generation=2,
            ranks=reassign_ranks([0, 2]),
            coordinator="127.0.0.1:4242",
            restore_step=9,
            handled=["worker-1"],
            ts=123.0,
        )
        assert rec["world_size"] == 3
        write_resize_record(tmp_path, rec)
        got = read_resize_record(tmp_path)
        assert got == rec
        assert not resize_record_path(tmp_path).with_suffix(
            ".json.tmp"
        ).exists()
        clear_resize_record(tmp_path)
        assert read_resize_record(tmp_path) is None
        clear_resize_record(tmp_path)  # idempotent

    def test_corrupt_record_reads_as_none(self, tmp_path):
        resize_record_path(tmp_path).write_text("{not json")
        assert read_resize_record(tmp_path) is None


class TestPollResize:
    def _arm(self, tmp_path, monkeypatch, ranks, generation=1, step=7):
        monkeypatch.setenv("TPUJOB_STATUS_DIR", str(tmp_path))
        write_resize_record(
            tmp_path,
            build_resize_record(
                generation=generation,
                ranks=ranks,
                coordinator="127.0.0.1:5151",
                restore_step=step,
                ts=1.0,
            ),
        )

    def _world(self, rtype="Worker", index=2, gen=0):
        return rendezvous.WorldInfo(
            num_processes=4,
            process_id=3,
            coordinator="127.0.0.1:23456",
            replica_type=rtype,
            replica_index=index,
            restart_count=0,
            job_key="default/ej",
            resize_generation=gen,
        )

    def test_member_adopts_new_coordinates(self, tmp_path, monkeypatch):
        self._arm(tmp_path, monkeypatch, reassign_ranks([0, 2]))
        sig = rendezvous.poll_resize(self._world())
        assert sig is not None and not sig.evicted
        assert sig.world.process_id == 2  # worker-2 compacted to rank 2
        assert sig.world.num_processes == 3
        assert sig.world.coordinator == "127.0.0.1:5151"
        assert sig.world.resize_generation == 1
        assert sig.restore_step == 7

    def test_absent_member_is_evicted(self, tmp_path, monkeypatch):
        self._arm(tmp_path, monkeypatch, reassign_ranks([0, 1]))
        sig = rendezvous.poll_resize(self._world(index=2))
        assert sig is not None and sig.evicted
        assert sig.world is None

    def test_current_generation_sees_nothing(self, tmp_path, monkeypatch):
        # A process already at the record's generation (it adopted, or
        # was spawned into it) must not re-trigger — the fence is
        # strictly monotone.
        self._arm(tmp_path, monkeypatch, reassign_ranks([0, 2]))
        assert rendezvous.poll_resize(self._world(gen=1)) is None
        assert rendezvous.poll_resize(self._world(gen=5)) is None

    def test_no_status_dir_is_a_noop(self, monkeypatch):
        monkeypatch.delenv("TPUJOB_STATUS_DIR", raising=False)
        assert rendezvous.poll_resize(self._world()) is None


def _ev(ts, reason, message=""):
    return {
        "timestamp": float(ts),
        "type": "Warning",
        "reason": reason,
        "message": message,
    }


def _window(events, now=200.0):
    from pytorch_operator_tpu.obs.watch import LiveWindow

    return LiveWindow(progress={}, records={}, events=events, now=now)


class TestResizeThrashRule:
    def test_fires_on_three_resizes_in_window(self):
        tl = _window(
            [
                _ev(100.0, "FaultInjected", "injected kill of w-1"),
                _ev(101.0, "ElasticScaledDown", "resized to 3"),
                _ev(130.0, "ElasticScaledUp", "grew back to 4"),
                _ev(160.0, "ElasticScaledDown", "resized to 3"),
            ]
        )
        found = obs_rules.detect_world_resize_thrash(tl)
        assert len(found) == 1
        f = found[0]
        assert f.rule == "world_resize_thrash"
        assert f.metrics["resizes"] == 3
        # The triggering death event rides along as evidence.
        assert any(
            e.get("reason") == "FaultInjected" for e in f.evidence
        )

    def test_quiet_below_count_or_outside_window(self):
        assert not obs_rules.detect_world_resize_thrash(
            _window(
                [
                    _ev(100.0, "ElasticScaledDown"),
                    _ev(110.0, "ElasticScaledUp"),
                ]
            )
        )
        # Three transitions, but spread wider than the window.
        assert not obs_rules.detect_world_resize_thrash(
            _window(
                [
                    _ev(100.0, "ElasticScaledDown"),
                    _ev(300.0, "ElasticScaledUp"),
                    _ev(500.0, "ElasticScaledDown"),
                ],
                now=600.0,
            )
        )

    def test_threshold_overrides_apply(self):
        events = [
            _ev(100.0, "ElasticScaledDown"),
            _ev(101.0, "ElasticSparePromoted"),
            _ev(102.0, "ElasticScaledUp"),
        ]
        th = obs_rules.thresholds_from_overrides({"resize_thrash_count": 5})
        assert not obs_rules.detect_world_resize_thrash(_window(events), th)
        th = obs_rules.thresholds_from_overrides(
            {"resize_thrash_count": 2, "resize_thrash_window_s": 0.5}
        )
        # Count met but no 2 transitions inside 0.5s... tighten window.
        assert not obs_rules.detect_world_resize_thrash(_window(events), th)
        th = obs_rules.thresholds_from_overrides(
            {"resize_thrash_count": 2, "resize_thrash_window_s": 10.0}
        )
        assert obs_rules.detect_world_resize_thrash(_window(events), th)

    def test_registered_in_both_inventories(self):
        assert "world_resize_thrash" in obs_rules.RULES
        assert obs_rules.detect_world_resize_thrash in obs_rules.DETECTORS
        assert "resize_thrash_count" in obs_rules.THRESHOLD_FIELDS


class TestNewFaultKinds:
    def test_kinds_validate_and_roundtrip(self):
        plan = FaultPlan(
            seed=3,
            faults=[
                Fault(kind="preempt_replica", target="worker-1", at=2),
                Fault(kind="kill_storm", target="worker-*", at=3, times=2),
            ],
        )
        got = FaultPlan.from_json(plan.to_json())
        assert [f.kind for f in got.faults] == [
            "preempt_replica",
            "kill_storm",
        ]

    def test_preempts_due_consumes_at_pass(self):
        inj = FaultInjector(
            FaultPlan(
                faults=[Fault(kind="preempt_replica", target="worker-0", at=2)]
            )
        )
        assert inj.preempts_due(1) == []
        due = inj.preempts_due(2)
        assert len(due) == 1 and due[0].target == "worker-0"
        assert inj.preempts_due(2) == []  # consumed

    def test_storm_consumed_whole_in_one_pass(self):
        # times is the victim budget of ONE burst, not a firing count:
        # the storm is due exactly once, at its pass.
        inj = FaultInjector(
            FaultPlan(
                faults=[Fault(kind="kill_storm", target="*", at=1, times=3)]
            )
        )
        due = inj.storms_due(1)
        assert len(due) == 1 and due[0].times == 3
        assert inj.storms_due(1) == []
        assert inj.storms_due(2) == []

    def test_record_maps_143_to_preempt_and_burst_to_storm(self, tmp_path):
        from pytorch_operator_tpu.controller.store import key_to_fs
        from pytorch_operator_tpu.faults.record import plan_from_recording

        state = tmp_path / "state"
        key = "default/storm"
        ev_dir = state / "events"
        ev_dir.mkdir(parents=True)
        death = (
            "replica default_storm-{} failed with exit code {} (restart #1)."
        )
        events = [
            # Two SIGKILLs one second apart: one correlated burst.
            {"timestamp": 100.0, "type": "Warning",
             "reason": "TPUJobRestarting",
             "message": death.format("worker-0", 137), "count": 1},
            {"timestamp": 101.0, "type": "Warning",
             "reason": "TPUJobRestarting",
             "message": death.format("worker-1", 137), "count": 1},
            # A SIGTERM eviction, minutes later.
            {"timestamp": 400.0, "type": "Warning",
             "reason": "TPUJobRestarting",
             "message": death.format("worker-2", 143), "count": 1},
        ]
        with open(ev_dir / (key_to_fs(key) + ".events.jsonl"), "a") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        plan = plan_from_recording(state, key)
        kinds = sorted(f.kind for f in plan.faults)
        assert kinds == ["kill_storm", "preempt_replica"]
        storm = next(f for f in plan.faults if f.kind == "kill_storm")
        assert storm.times == 2
        pre = next(f for f in plan.faults if f.kind == "preempt_replica")
        assert pre.target == "worker-2"
        # The reconstructed plan replays through a fresh injector.
        inj = FaultInjector(plan)
        assert len(inj.storms_due(1)) == 1
        assert len(inj.preempts_due(1)) == 1
