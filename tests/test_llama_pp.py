"""Pipeline-parallel Llama training (pp in the flagship workload).

Round-1 left pipeline_apply validated only standalone; here the SAME
trained model runs through the pp path (models.llama.forward_pp via
make_lm_train_step) on a dp×pp mesh and must reproduce the sequential
run's losses step for step — the VERDICT round-2 "pp in the flagship"
requirement.
"""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import llama as llama_lib
from pytorch_operator_tpu.parallel import make_mesh
from pytorch_operator_tpu.workloads.trainer import (
    init_sharded_train_state,
    make_lm_train_step,
)

# Fast-lane exclusion (-m 'not slow'): pp-schedule numerics parity,
# ~30-60s per test.
pytestmark = pytest.mark.slow


def _tokens(b=8, s=16, seed=0):
    import jax.numpy as jnp

    rng = np.random.default_rng(seed)
    return jnp.asarray(rng.integers(0, 256, (b, s)), jnp.int32)


def _train(cfg, mesh_spec, tokens, steps=3, microbatches=None, pp_schedule="gpipe"):
    import jax
    import numpy as np_
    import optax

    mesh = make_mesh(mesh_spec)
    model = llama_lib.Llama(cfg, mesh=mesh)
    tx = optax.adamw(1e-3)
    state, _ = init_sharded_train_state(
        lambda k: model.init(k, np_.zeros((1, tokens.shape[1]), np_.int32)),
        tx,
        mesh,
    )
    step = make_lm_train_step(
        model, tx, mesh, microbatches=microbatches, pp_schedule=pp_schedule
    )
    losses = []
    for _ in range(steps):
        state, loss = step(state, tokens)
        losses.append(float(jax.device_get(loss)))
    return losses


class TestLlamaPipelineParallel:
    @pytest.mark.parametrize(
        "xent_impl,remat",
        [("dense", False), ("chunked", False), ("dense", True)],
    )
    def test_dp_pp_matches_sequential(self, xent_impl, remat):
        """dp=2 x pp=4 llama train == dp=8 sequential train, step for
        step (same init seed via TPUJOB_SEED default)."""
        cfg = llama_lib.llama_tiny(
            n_layers=4, attn_impl="dense", xent_impl=xent_impl, remat=remat
        )
        tokens = _tokens()
        pp_losses = _train(cfg, "dp=2,pp=4", tokens)
        seq_losses = _train(cfg, "dp=8", tokens)
        np.testing.assert_allclose(pp_losses, seq_losses, rtol=2e-5)
        assert pp_losses[-1] < pp_losses[0]  # it actually trains

    def test_custom_microbatches(self):
        cfg = llama_lib.llama_tiny(n_layers=4, attn_impl="dense")
        tokens = _tokens()
        # 4 differs from the 2*pp=8 default, so a regression that drops
        # the microbatches argument cannot sneak past.
        pp_losses = _train(cfg, "dp=2,pp=4", tokens, microbatches=4)
        seq_losses = _train(cfg, "dp=8", tokens)
        np.testing.assert_allclose(pp_losses, seq_losses, rtol=2e-5)

    @pytest.mark.parametrize("xent_impl", ["dense", "chunked"])
    def test_1f1b_matches_sequential(self, xent_impl):
        """--pp-schedule 1f1b (the fused bounded-residency schedule,
        VERDICT r2 Missing #4) must train step-for-step identically to
        the sequential run — through the full workload train step
        (embedding grads via the dx stream, norm/head grads at the last
        stage, optimizer update on the re-boxed tree)."""
        cfg = llama_lib.llama_tiny(
            n_layers=4, attn_impl="dense", xent_impl=xent_impl
        )
        tokens = _tokens()
        f1_losses = _train(cfg, "dp=2,pp=4", tokens, pp_schedule="1f1b")
        seq_losses = _train(cfg, "dp=8", tokens)
        np.testing.assert_allclose(f1_losses, seq_losses, rtol=2e-5)
        assert f1_losses[-1] < f1_losses[0]

    def test_1f1b_pp1_degenerate_keeps_chunked_tail(self):
        """pp=1 via the direct hook (the trainer refuses extent-1 pp and
        runs the sequential step instead): no loss duplication exists, so
        the vocab-parallel chunk (which would be the FULL vocab) must not
        replace the chunked xent tail — and numerics must still match
        plain autodiff."""
        import jax
        import optax

        cfg = llama_lib.llama_tiny(
            n_layers=4, attn_impl="dense", xent_impl="chunked"
        )
        tokens = _tokens()
        mesh = make_mesh("dp=8,pp=1")
        model = llama_lib.Llama(cfg, mesh=mesh)
        params = model.init(jax.random.key(0), tokens[:1])["params"]

        loss, grads = jax.jit(
            lambda p, t: llama_lib.train_value_and_grad_pp(
                model, p, t, mesh=mesh, microbatches=4
            )
        )(params, tokens)

        def seq_loss(p, toks):
            logits = model.apply({"params": p}, toks)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], toks[:, 1:]
            ).mean()

        ref_loss, ref_grads = jax.value_and_grad(seq_loss)(params, tokens)
        assert float(loss) == pytest.approx(float(ref_loss), rel=2e-5)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            grads,
            ref_grads,
        )

    def test_vocab_not_divisible_by_pp_warns_and_falls_back(self):
        """A vocab that doesn't divide pp can't be vocab-parallel: the
        tail falls back to the replicated (pre-round-4) form with a
        warning — numerics must still match the sequential run."""
        import warnings

        cfg = llama_lib.llama_tiny(
            vocab_size=254, n_layers=4, attn_impl="dense"
        )
        tokens = _tokens()
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            f1_losses = _train(
                cfg, "dp=2,pp=4", tokens, pp_schedule="1f1b"
            )
        assert any("does not divide pp" in str(w.message) for w in caught)
        seq_losses = _train(cfg, "dp=8", tokens)
        np.testing.assert_allclose(f1_losses, seq_losses, rtol=2e-5)

    @pytest.mark.parametrize("xent_impl", ["dense", "chunked"])
    def test_1f1b_vocab_parallel_tail_honors_xent_impl(self, xent_impl):
        """The vocab-parallel tail must stream sub-chunks under
        xent_impl='chunked' (memory contract) while matching the dense
        tail's numerics — pinned by training the same data both ways."""
        cfg = llama_lib.llama_tiny(
            n_layers=4, attn_impl="dense", xent_impl=xent_impl,
            vocab_size=256,
        )
        tokens = _tokens()
        f1 = _train(cfg, "dp=2,pp=4", tokens, pp_schedule="1f1b")
        seq = _train(cfg, "dp=8", tokens)
        np.testing.assert_allclose(f1, seq, rtol=2e-5)

    def test_bad_pp_schedule_rejected(self):
        cfg = llama_lib.llama_tiny(n_layers=4, attn_impl="dense")
        tokens = _tokens()
        with pytest.raises(ValueError, match="pp_schedule"):
            _train(cfg, "dp=2,pp=4", tokens, steps=1, pp_schedule="zigzag")

    def test_1f1b_without_pp_axis_rejected(self):
        """--pp-schedule 1f1b on a mesh with no pp axis must fail fast,
        not silently run the sequential step (a typo'd mesh spec would
        otherwise masquerade as a 1F1B measurement)."""
        cfg = llama_lib.llama_tiny(n_layers=4, attn_impl="dense")
        tokens = _tokens()
        with pytest.raises(ValueError, match="no pp axis"):
            _train(cfg, "dp=8", tokens, steps=1, pp_schedule="1f1b")

    def test_layers_not_divisible_rejected(self):
        cfg = llama_lib.llama_tiny(n_layers=3, attn_impl="dense")
        tokens = _tokens()
        with pytest.raises(ValueError, match="n_layers"):
            _train(cfg, "dp=2,pp=4", tokens, steps=1)

    def test_ring_inside_pp_rejected(self):
        cfg = llama_lib.llama_tiny(n_layers=4, attn_impl="ring")
        tokens = _tokens()
        with pytest.raises(ValueError, match="ring"):
            _train(cfg, "dp=2,pp=4", tokens, steps=1)
