"""HA failover end-to-end: leader daemon dies, the hot standby acquires
the lease and ADOPTS the live world — no duplicate replicas, no lost job.

This composes the two restart-safety mechanisms that are otherwise tested
separately: the flock leader lease (released by the kernel on holder
death, tests/test_monitoring.py) and replica adoption from persisted
records (tests/test_adoption.py). The reference gets the same property
from k8s leader election + pods living in the API server.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import pytest

# Fast-lane exclusion (-m 'not slow'): real-subprocess HA leader failover.
pytestmark = pytest.mark.slow

REPO_ROOT = str(Path(__file__).resolve().parents[1])


def spawn_daemon(state_dir, log_path):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    env["TPUJOB_PLATFORM"] = "cpu"
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "pytorch_operator_tpu.client.cli",
            "--state-dir",
            str(state_dir),
            "supervisor",
            "--interval",
            "0.2",
        ],
        env=env,
        stdout=open(log_path, "ab"),
        stderr=subprocess.STDOUT,
    )


def wait_for(cond, timeout, interval=0.2):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return False


def job_state(state_dir, key):
    p = state_dir / "jobs" / (key.replace("/", "_") + ".json")
    if not p.exists():
        return None
    try:
        return json.loads(p.read_text())
    except ValueError:
        return None


def test_leader_crash_standby_adopts_world(tmp_path):
    state = tmp_path / "state"
    state.mkdir()
    # Leader + hot standby share the state dir; the standby blocks on the
    # lease until the leader dies.
    d1 = spawn_daemon(state, tmp_path / "d1.log")
    d2 = spawn_daemon(state, tmp_path / "d2.log")

    def log(name):
        p = tmp_path / name
        return p.read_text() if p.exists() else ""

    # flock acquisition order is NOT spawn order: identify the actual
    # holder before killing, or the test can pass without ever exercising
    # failover (killing the standby proves nothing).
    assert wait_for(
        lambda: ("standby —" in log("d1.log")) != ("standby —" in log("d2.log")),
        30,
    ), "could not identify a unique standby from the daemon logs"
    if "standby —" in log("d1.log"):
        standby, leader = d1, d2
    else:
        standby, leader = d2, d1
    try:
        # Submit a job whose master sleeps long enough to straddle failover.
        spec = {
            "api_version": "tpujob.dev/v1",
            "kind": "TPUJob",
            "metadata": {"name": "ha"},
            "spec": {
                "replica_specs": {
                    "Master": {
                        "replicas": 1,
                        "template": {
                            "command": ["sh", "-c", "sleep 12; echo ha-done"]
                        },
                    }
                }
            },
        }
        from pytorch_operator_tpu.api import job_from_dict
        from pytorch_operator_tpu.controller.store import JobStore

        store = JobStore(persist_dir=state / "jobs")
        key = store.add(job_from_dict(spec))

        # The (single) active daemon launches the replica.
        rec_dir = state / "replicas"
        assert wait_for(
            lambda: rec_dir.is_dir() and list(rec_dir.glob("*.json")), 30
        ), "leader never launched the replica"
        rec_file = next(rec_dir.glob("*.json"))
        pid_before = json.loads(rec_file.read_text())["pid"]

        # Kill the leader without cleanup: the replica must survive and the
        # standby must take over.
        os.kill(leader.pid, signal.SIGKILL)
        leader.wait(timeout=10)

        def succeeded():
            rec = job_state(state, key)
            if rec is None:
                return False
            return any(
                c.get("type") == "Succeeded" and c.get("status")
                for c in rec.get("status", {}).get("conditions", [])
            )

        assert wait_for(succeeded, 60), "standby never completed the job"

        # One creation only — the standby ADOPTED pid_before, it did not
        # double-create the world.
        from pytorch_operator_tpu.controller.events import load_merged_events

        creates = [
            rec
            for rec in load_merged_events(state / "events" / "default_ha.events.jsonl")
            if rec["reason"] == "SuccessfulCreateReplica"
        ]
        # One creation, once: the aggregation write-through would surface
        # a double-create as count>1 even within one merged record.
        assert len(creates) == 1 and int(creates[0].get("count", 1)) == 1, creates
        # And the log shows exactly one run of the workload.
        log = (state / "logs" / "default_ha-master-0.log").read_text()
        assert log.count("ha-done") == 1
        assert pid_before is not None
    finally:
        for proc in (d1, d2):
            if proc.poll() is None:
                proc.terminate()
                try:
                    proc.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    proc.kill()


def test_rescan_replaces_stale_standby_snapshot(tmp_path):
    """A standby that adopted a replica at ITS startup must, at takeover,
    prefer the disk record (the leader may have restarted the replica
    under a new pid while the standby waited)."""
    from pytorch_operator_tpu.api.types import ProcessTemplate, ReplicaType
    from pytorch_operator_tpu.controller.runner import SubprocessRunner

    leader = SubprocessRunner(tmp_path)
    t = ProcessTemplate(command=["sleep", "30"])
    h1 = leader.create("default/j", ReplicaType.MASTER, 0, t, {})
    standby = SubprocessRunner(tmp_path)  # snapshots pid of h1
    assert standby.get(h1.name).pid == h1.pid
    # The leader restarts the replica: new pid under the same name.
    leader.delete(h1.name, grace_seconds=1.0)
    h2 = leader.create("default/j", ReplicaType.MASTER, 0, t, {})
    assert h2.pid != h1.pid
    # Takeover: the standby must track the NEW incarnation, not classify
    # the old pid as dead and double-create.
    standby.rescan()
    got = standby.get(h2.name)
    assert got.pid == h2.pid
    assert got.is_active()
    standby.delete(h2.name, grace_seconds=1.0)
    leader.shutdown()


def test_startup_load_is_read_only(tmp_path):
    """Constructing a runner over another incarnation's records must not
    WRITE to them — a mere standby classifying a dead pid would clobber
    state the live leader still owns."""
    from pytorch_operator_tpu.api.types import ProcessTemplate, ReplicaType
    from pytorch_operator_tpu.controller.runner import SubprocessRunner

    leader = SubprocessRunner(tmp_path)
    t = ProcessTemplate(command=["sh", "-c", "exit 0"])
    h = leader.create("default/j", ReplicaType.MASTER, 0, t, {})
    assert wait_for(
        lambda: leader._read_exit_file(h.name) is not None, 15
    )
    rec_path = leader._record_path(h.name)
    before = rec_path.read_text()
    standby = SubprocessRunner(tmp_path)
    # In-memory classification happened...
    assert standby.get(h.name).is_finished()
    # ...but the record on disk is untouched (still says RUNNING).
    assert rec_path.read_text() == before
    leader.shutdown()
