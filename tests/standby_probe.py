"""Tiny probe workload for standby tests: prints an env var, optionally
sleeps, exits with a configurable code."""

import os
import sys
import time


def main() -> int:
    print("probe-env", os.environ.get("PROBE_VAL", ""), flush=True)
    if os.environ.get("PROBE_SPAWN_CHILD"):
        # A same-process-group descendant that outlives the main process
        # (data-loader-worker stand-in for the wrapperless-death test).
        import subprocess

        subprocess.Popen(["sleep", os.environ["PROBE_SPAWN_CHILD"]])
    if os.environ.get("PROBE_SLEEP"):
        time.sleep(float(os.environ["PROBE_SLEEP"]))
    return int(os.environ.get("PROBE_EXIT", "0"))


if __name__ == "__main__":
    sys.exit(main())
