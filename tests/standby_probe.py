"""Tiny probe workload for standby tests: prints an env var, optionally
sleeps, exits with a configurable code."""

import os
import sys
import time


def main() -> int:
    print("probe-env", os.environ.get("PROBE_VAL", ""), flush=True)
    if os.environ.get("PROBE_DUMP_ENV"):
        # Full-environment fingerprint for the cold-vs-warm parity test
        # (one line per var; the json module keeps newlines escaped).
        import json

        print("probe-environ", json.dumps(dict(os.environ)), flush=True)
    if os.environ.get("PROBE_SPAWN_CHILD"):
        # A same-process-group descendant that outlives the main process
        # (data-loader-worker stand-in for the wrapperless-death test).
        import subprocess

        subprocess.Popen(["sleep", os.environ["PROBE_SPAWN_CHILD"]])
    if os.environ.get("PROBE_SLEEP"):
        time.sleep(float(os.environ["PROBE_SLEEP"]))
    if os.environ.get("PROBE_WAIT_FOR_GLOB"):
        # Deterministic capacity-release hook: occupy our slots until some
        # path matching the glob exists (e.g. another job's first
        # checkpoint), then exit 0.
        import glob

        while not glob.glob(os.environ["PROBE_WAIT_FOR_GLOB"]):
            time.sleep(0.1)
    return int(os.environ.get("PROBE_EXIT", "0"))


if __name__ == "__main__":
    sys.exit(main())
