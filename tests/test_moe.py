"""Expert-parallel MoE tests on the virtual CPU mesh."""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.parallel import make_mesh
from pytorch_operator_tpu.parallel.moe import moe_mlp

# Fast-lane exclusion (-m 'not slow'): MoE training + dispatch parity runs.
pytestmark = pytest.mark.slow


def _params(e, d, f, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "gate": (rng.standard_normal((d, e)) * 0.5).astype(np.float32),
        "w_in": (rng.standard_normal((e, d, f)) * 0.3).astype(np.float32),
        "w_out": (rng.standard_normal((e, f, d)) * 0.3).astype(np.float32),
    }


def _reference(params, x, top_k):
    """Unsharded dense reference: per-token sum of gated expert FFNs."""
    import jax
    import jax.numpy as jnp

    logits = x @ params["gate"]
    top_vals, top_idx = jax.lax.top_k(logits, top_k)
    probs = jax.nn.softmax(top_vals, axis=-1)
    out = jnp.zeros_like(x)
    for e in range(params["w_in"].shape[0]):
        h = jax.nn.gelu(x @ params["w_in"][e])
        y = h @ params["w_out"][e]
        gate_e = ((top_idx == e) * probs).sum(axis=-1)
        out = out + y * gate_e[:, None]
    return out


class TestMoE:
    @pytest.mark.parametrize("top_k", [1, 2])
    @pytest.mark.parametrize("ep", [2, 4, 8])
    def test_matches_dense_reference(self, top_k, ep):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh(f"ep={ep}", devices=jax.devices()[:ep])
        params = jax.tree.map(jnp.asarray, _params(8, 6, 12))
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((10, 6)).astype(np.float32)
        )
        out = moe_mlp(params, x, mesh=mesh, top_k=top_k)
        ref = _reference(params, x, top_k)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_grads_match_reference(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("ep=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _params(4, 6, 8, seed=2))
        x = jnp.asarray(
            np.random.default_rng(3).standard_normal((6, 6)).astype(np.float32)
        )

        gp = jax.grad(lambda p: (moe_mlp(p, x, mesh=mesh, top_k=2) ** 2).mean())(params)
        gr = jax.grad(lambda p: (_reference(p, x, 2) ** 2).mean())(params)
        for k in ("gate", "w_in", "w_out"):
            np.testing.assert_allclose(
                np.asarray(gp[k]), np.asarray(gr[k]), rtol=1e-4, atol=1e-5
            )

    def test_under_jit(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("ep=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _params(4, 6, 8))
        x = jnp.ones((4, 6), jnp.float32)
        out = jax.jit(lambda p, x: moe_mlp(p, x, mesh=mesh, top_k=1))(params, x)
        ref = _reference(params, x, 1)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_llama_moe_ep_matches_dense_fallback(self):
        """The MoE llama on an ep mesh must compute exactly what the same
        params compute through the meshless dense-reference path."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.models import llama as llama_lib

        cfg = llama_lib.llama_tiny(n_experts=4, moe_top_k=2)
        mesh = make_mesh("ep=4", devices=jax.devices()[:4])
        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, (2, 16)), jnp.int32
        )
        model_ep = llama_lib.Llama(cfg, mesh=mesh)
        variables = model_ep.init(jax.random.key(0), tokens)
        out_ep = model_ep.apply(variables, tokens)
        out_ref = llama_lib.Llama(cfg).apply(variables, tokens)
        np.testing.assert_allclose(
            np.asarray(out_ep), np.asarray(out_ref), rtol=2e-2, atol=2e-2
        )

    def test_llama_moe_trains(self):
        """End-to-end: MoE llama trains through the shared trainer on an
        ep-bearing mesh; loss decreases from chance."""
        from pytorch_operator_tpu.workloads import llama_train

        result = llama_train.run(
            config="tiny", mesh_spec="dp=2,ep=4", batch_size=8, seq_len=32,
            steps=25, warmup=1, lr=1e-3, n_experts=4, log=lambda *_: None,
        )
        assert result["final_loss"] < 5.2, result

    @pytest.mark.parametrize("ep", [1, 4])
    def test_sparse_matches_reference_with_ample_capacity(self, ep):
        """Capacity-factor dispatch with capacity >= every expert's demand
        drops nothing — it must reproduce the exact renormalized top-k
        routing, unsharded and ep-sharded."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel.moe import moe_mlp_sparse

        E = 8
        params = jax.tree.map(jnp.asarray, _params(E, 6, 12))
        x = jnp.asarray(
            np.random.default_rng(1).standard_normal((16, 6)).astype(np.float32)
        )
        mesh = make_mesh(f"ep={ep}", devices=jax.devices()[:ep]) if ep > 1 else None
        out = moe_mlp_sparse(
            params, x, top_k=2, capacity_factor=float(E) / 2, group_size=8,
            mesh=mesh,
        )
        ref = _reference(params, x, top_k=2)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_sparse_tight_capacity_drops_not_corrupts(self):
        """Over-capacity tokens vanish (zero contribution), everything
        else stays exact: the output never diverges beyond the dropped
        tokens' share and stays finite."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel.moe import moe_mlp_sparse

        params = jax.tree.map(jnp.asarray, _params(8, 6, 12))
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((32, 6)).astype(np.float32)
        )
        out = moe_mlp_sparse(
            params, x, top_k=2, capacity_factor=1.0, group_size=32
        )
        ref = _reference(params, x, top_k=2)
        assert bool(jnp.isfinite(out).all())
        # With cf=1.0 and skewed routing SOME tokens drop; each row is
        # either exact or a strict subset of its expert contributions.
        row_close = np.isclose(
            np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
        ).all(axis=1)
        assert row_close.any(), "everything dropped — dispatch broken"

    def test_sparse_grads_flow(self):
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel.moe import moe_mlp_sparse

        params = jax.tree.map(jnp.asarray, _params(8, 6, 12))
        x = jnp.asarray(
            np.random.default_rng(3).standard_normal((16, 6)).astype(np.float32)
        )
        g = jax.grad(
            lambda p: (
                moe_mlp_sparse(p, x, top_k=2, capacity_factor=4.0, group_size=8)
                ** 2
            ).mean()
        )(params)
        assert all(
            bool(jnp.isfinite(leaf).all()) for leaf in jax.tree.leaves(g)
        )
        assert any(
            float(jnp.abs(leaf).max()) > 0 for leaf in jax.tree.leaves(g)
        )

    def test_load_balance_loss_values(self):
        """Balanced routing scores ~1.0; a collapsed router scores ~E."""
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel.moe import load_balance_loss

        E, D, N = 8, 16, 512
        rng = np.random.default_rng(5)
        x = jnp.asarray(rng.standard_normal((N, D)), jnp.float32)
        # Zero gate → uniform router → balanced floor.
        balanced = {"gate": jnp.zeros((D, E), jnp.float32)}
        lb = float(load_balance_loss(balanced, x, top_k=2))
        assert 0.9 < lb < 1.3, lb
        # A gate with a huge bias toward expert 0 (positive inputs) →
        # collapsed routing → ~E.
        collapsed = {"gate": jnp.zeros((D, E), jnp.float32).at[0, 0].set(100.0)}
        lc = float(load_balance_loss(collapsed, jnp.abs(x), top_k=1))
        assert lc > E * 0.8, lc

    def test_aux_loss_spreads_the_router(self):
        """Training WITH the aux loss must end more balanced than
        without it (measured by the load-balance metric itself)."""
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_operator_tpu.models import llama as llama_lib
        from pytorch_operator_tpu.parallel.moe import load_balance_loss
        from pytorch_operator_tpu.workloads.trainer import (
            init_sharded_train_state,
            make_lm_train_step,
        )

        def train(aux_weight):
            cfg = llama_lib.llama_tiny(
                n_experts=8, attn_impl="dense", moe_aux_weight=aux_weight
            )
            mesh = make_mesh("dp=1", devices=jax.devices()[:1])
            model = llama_lib.Llama(cfg, mesh=mesh)
            tokens = jnp.asarray(
                np.random.default_rng(9).integers(0, 256, (8, 32)), jnp.int32
            )
            tx = optax.adamw(3e-3)
            state, _ = init_sharded_train_state(
                lambda k: model.init(k, np.zeros((1, 32), np.int32)), tx, mesh
            )
            step = make_lm_train_step(model, tx, mesh)
            for _ in range(12):
                state, loss = step(state, tokens)
            assert np.isfinite(float(loss))
            # Measure final balance through layer 0's router.
            import flax.linen as nn

            p = nn.meta.unbox(state["params"])
            gate0 = jax.tree.map(lambda l: l[0], p["layers"]["moe_mlp"])
            x = jnp.asarray(
                np.random.default_rng(10).standard_normal((256, 64)),
                jnp.float32,
            )
            return float(load_balance_loss({"gate": gate0["gate"]}, x, 2))

        lb_with = train(aux_weight=0.05)
        lb_without = train(aux_weight=0.0)
        assert lb_with <= lb_without + 1e-3, (lb_with, lb_without)

    def test_llama_sparse_moe_trains(self):
        """cfg.moe_dispatch='sparse' through the full workload on an ep
        mesh: trains to the same loss neighborhood as dense dispatch."""
        from pytorch_operator_tpu.workloads import llama_train

        result = llama_train.run(
            config="tiny", mesh_spec="dp=2,ep=4", batch_size=8, seq_len=32,
            steps=25, warmup=1, lr=1e-3, n_experts=4,
            moe_dispatch="sparse", log=lambda *_: None,
        )
        assert result["final_loss"] < 5.2, result

    @pytest.mark.parametrize("spec", ["ep=2,tp=4", "fsdp=2,ep=2,tp=2", "fsdp=4,ep=2"])
    def test_matches_reference_on_composite_meshes(self, spec):
        """Expert weights stay tp/fsdp-sharded inside the dispatch (tp
        column/row-parallel over F, ZeRO gather over D) — the result must
        still match the dense reference exactly."""
        import jax
        import jax.numpy as jnp

        mesh = make_mesh(spec, devices=jax.devices()[:8])
        params = jax.tree.map(jnp.asarray, _params(4, 6, 8, seed=4))
        x = jnp.asarray(
            np.random.default_rng(5).standard_normal((8, 6)).astype(np.float32)
        )
        out = moe_mlp(params, x, mesh=mesh, top_k=2)
        ref = _reference(params, x, 2)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-5)

    def test_composite_mesh_grads_match(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("fsdp=2,ep=2,tp=2", devices=jax.devices()[:8])
        params = jax.tree.map(jnp.asarray, _params(4, 6, 8, seed=6))
        x = jnp.asarray(
            np.random.default_rng(7).standard_normal((8, 6)).astype(np.float32)
        )
        gp = jax.grad(lambda p: (moe_mlp(p, x, mesh=mesh, top_k=2) ** 2).mean())(params)
        gr = jax.grad(lambda p: (_reference(p, x, 2) ** 2).mean())(params)
        for k in ("gate", "w_in", "w_out"):
            np.testing.assert_allclose(
                np.asarray(gp[k]), np.asarray(gr[k]), rtol=1e-4, atol=1e-5
            )

    def test_expert_weights_not_gathered_over_tp(self):
        """TP must never gather weights: the compiled dispatch keeps w_in's
        F dim sharded over tp (local shard shape F/tp), rather than
        replicating it via an all-gather."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = make_mesh("ep=2,tp=4", devices=jax.devices()[:8])
        params = jax.tree.map(jnp.asarray, _params(4, 6, 8))
        params["w_in"] = jax.device_put(
            params["w_in"], NamedSharding(mesh, P("ep", None, "tp"))
        )
        params["w_out"] = jax.device_put(
            params["w_out"], NamedSharding(mesh, P("ep", "tp", None))
        )
        x = jnp.ones((8, 6), jnp.float32)
        lowered = jax.jit(
            lambda p, x: moe_mlp(p, x, mesh=mesh, top_k=2)
        ).lower(params, x)
        hlo = lowered.compile().as_text()
        # Any all-gather in the program may only be over token rows; a
        # full-size [E, D, F] = 4x6x8 weight must not appear as ANY
        # gather's result (check every occurrence, not just the first).
        for seg in hlo.split("all-gather")[1:]:
            assert "4,6,8" not in seg[:200], (
                "w_in appears to be all-gathered to full size under tp"
            )

    def test_bad_expert_split_rejected(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("ep=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _params(6, 4, 8))  # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            moe_mlp(params, jnp.zeros((4, 4)), mesh=mesh)

    def test_sparse_without_aux_warns(self):
        """VERDICT r2 Weak #5: sparse dispatch is the recommended config
        at E>=16 while moe_aux_weight defaults to 0 — exactly the
        combination whose router collapse silently DROPS tokens. The
        config must warn at construction; the safe variants must not."""
        import warnings

        from pytorch_operator_tpu.models.llama import llama_tiny

        with pytest.warns(UserWarning, match="collapse"):
            llama_tiny(n_experts=4, moe_dispatch="sparse")
        with warnings.catch_warnings():
            warnings.simplefilter("error")
            llama_tiny(n_experts=4, moe_dispatch="sparse", moe_aux_weight=1e-2)
            llama_tiny(n_experts=4, moe_dispatch="dense")
            llama_tiny(moe_dispatch="sparse")  # dense model: dispatch inert

    def test_workload_logs_sparse_no_aux_warning(self):
        """The same guard on the job-log surface (what an operator's user
        actually reads)."""
        from pytorch_operator_tpu.workloads import llama_train

        logs = []
        llama_train.run(
            config="tiny", mesh_spec="dp=2,ep=4", batch_size=8, seq_len=16,
            steps=1, warmup=1, n_experts=4, moe_dispatch="sparse",
            log=logs.append,
        )
        assert any("DROPS most tokens" in m for m in logs), logs
        logs = []
        llama_train.run(
            config="tiny", mesh_spec="dp=2,ep=4", batch_size=8, seq_len=16,
            steps=1, warmup=1, n_experts=4, moe_dispatch="sparse",
            moe_aux_weight=1e-2, log=logs.append,
        )
        assert not any("DROPS most tokens" in m for m in logs), logs

    def test_workload_rejects_top_k_above_experts(self):
        """--experts below the default top_k must fail fast with a clear
        message, not a ValueError deep inside model tracing."""
        from pytorch_operator_tpu.workloads import llama_train

        with pytest.raises(ValueError, match="moe_top_k"):
            llama_train.run(
                config="tiny", mesh_spec="dp=1", batch_size=2, seq_len=8,
                steps=1, warmup=0, n_experts=1, log=lambda *_: None,
            )

    def test_bad_top_k_rejected(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("ep=2", devices=jax.devices()[:2])
        params = jax.tree.map(jnp.asarray, _params(4, 4, 8))
        with pytest.raises(ValueError, match="top_k"):
            moe_mlp(params, jnp.zeros((4, 4)), mesh=mesh, top_k=9)
