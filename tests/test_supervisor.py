"""Supervisor tests: real SubprocessRunner end-to-end with trivial workloads,
TTL GC, persistence, elastic scale, metrics rendering.
"""

import time

import pytest

from pytorch_operator_tpu.api import (
    CleanPodPolicy,
    ConditionType,
    ElasticPolicy,
    ProcessTemplate,
    ReplicaPhase,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    ValidationError,
)
from pytorch_operator_tpu.controller import (
    JobStore,
    Supervisor,
    schedule_to_first_step_latency,
)
from pytorch_operator_tpu.controller.runner import replica_name
from tests.testutil import new_job


def make_supervisor(tmp_path, **kw):
    return Supervisor(state_dir=tmp_path / "state", poll_interval=0.05, **kw)


class TestSubprocessE2E:
    def test_noop_job_succeeds(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="noop-e2e", workers=1)
        done = sup.run(job, timeout=30)
        assert done.is_succeeded()
        assert done.status.completion_time is not None
        # first-step report flowed back through the status dir
        assert done.status.first_step_time is not None
        lat = schedule_to_first_step_latency(done)
        assert lat is not None and 0 <= lat < 30
        sup.shutdown()

    def test_resubmit_after_cross_process_delete_actually_runs(self, tmp_path):
        """`tpujob delete` with no daemon running removes the STORE record
        and leaves replica records for the marker consumer. A fresh
        supervisor resubmitting the same job must reap those stale
        records, not adopt the old master's exit file and declare the new
        job Succeeded without running anything (round-2 regression)."""
        import time as _time

        sup = make_supervisor(tmp_path)
        job = new_job(name="re-run", workers=0)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            command=["sh", "-c", "sleep 0.2; exit 0"]
        )
        key = None
        try:
            done = sup.run(job, timeout=30)
            key = "default/re-run"
            assert done.is_succeeded()
            # CLI-style cross-process delete: marker + record removal only.
            sup.store.mark_deletion(key)
            sup.store.delete(key)
        finally:
            sup.shutdown()

        sup2 = make_supervisor(tmp_path)
        try:
            t0 = _time.time()
            job2 = new_job(name="re-run", workers=0)
            job2.spec.replica_specs[ReplicaType.MASTER].template = (
                ProcessTemplate(command=["sh", "-c", "sleep 0.2; exit 0"])
            )
            done2 = sup2.run(job2, timeout=30)
            assert done2.is_succeeded()
            h = sup2.runner.get(replica_name(key, ReplicaType.MASTER, 0))
            assert h is not None and h.created_at >= t0, (
                "new incarnation adopted the deleted run's stale record "
                "instead of actually running"
            )
        finally:
            sup2.shutdown()

    def test_apply_after_cross_process_delete_does_not_deadlock(self, tmp_path):
        """apply() holds the per-key lock and calls submit(), whose
        stale-incarnation reap calls delete_job() — which re-acquires the
        same key lock. With a non-reentrant lock this deadlocked; the
        RLock must let the nested teardown proceed."""
        import threading

        sup = make_supervisor(tmp_path)
        tmpl = ProcessTemplate(command=["sh", "-c", "sleep 0.2; exit 0"])
        job = new_job(name="ap-re", workers=0)
        job.spec.replica_specs[ReplicaType.MASTER].template = tmpl
        try:
            done = sup.run(job, timeout=30)
            assert done.is_succeeded()
            sup.store.mark_deletion("default/ap-re")
            sup.store.delete("default/ap-re")
        finally:
            sup.shutdown()

        sup2 = make_supervisor(tmp_path)
        try:
            job2 = new_job(name="ap-re", workers=0)
            job2.spec.replica_specs[ReplicaType.MASTER].template = tmpl
            result = {}
            t = threading.Thread(
                target=lambda: result.update(key=sup2.apply(job2))
            )
            t.start()
            t.join(timeout=20)
            assert not t.is_alive(), "apply() deadlocked on the key lock"
            assert result["key"] == "default/ap-re"
        finally:
            sup2.shutdown()

    def test_deletion_marker_legacy_formats_keep_purge_request(self, tmp_path):
        """Markers written by older code (bare 'purge' string; transitional
        JSON with a bare purge bool) must still purge — and the current
        mode-based payload only contains the literal 'purge' when purging
        (legacy substring readers must not purge plain deletes)."""
        import json as _json

        sup = make_supervisor(tmp_path)
        try:
            store = sup.store
            key = "default/legacy"
            # Current format: plain delete carries no 'purge' substring.
            store.mark_deletion(key, purge=False, uid="u")
            marker = store._marker_path(key, "delete")
            assert "purge" not in marker.read_text()
            assert store.marker_requests_purge(key) is False
            store.mark_deletion(key, purge=True, uid="u")
            assert store.marker_requests_purge(key) is True
            # Transitional JSON format (bare bool).
            marker.write_text(_json.dumps({"purge": True, "uid": "u"}))
            assert store.marker_requests_purge(key) is True
            # Legacy string format.
            marker.write_text("purge")
            assert store.marker_requests_purge(key) is True
            marker.write_text("")
            assert store.marker_requests_purge(key) is False
            marker.unlink()
        finally:
            sup.shutdown()

    def test_unknown_age_finished_records_reaped_active_spared(self, tmp_path):
        """uid-mismatch marker processing with legacy records that lack
        created_at (0.0): FINISHED stale records are reaped (they would
        be adopted as phantom success), ACTIVE unknown-age replicas are
        spared (never kill what might be the new job's world)."""
        sup = make_supervisor(tmp_path)
        try:
            job = new_job(name="age", workers=1)
            tmpl = ProcessTemplate(command=["sleep", "30"])
            for rs in job.spec.replica_specs.values():
                rs.template = tmpl
            key = sup.submit(job)
            sup.sync_once()
            handles = sup.runner.list_for_job(key)
            assert len(handles) == 2
            # Simulate legacy records: ages unknown; master finished.
            master = sup.runner.get(replica_name(key, ReplicaType.MASTER, 0))
            worker = sup.runner.get(replica_name(key, ReplicaType.WORKER, 0))
            master.created_at = 0.0
            worker.created_at = 0.0
            master.phase = ReplicaPhase.SUCCEEDED
            master.exit_code = 0
            # Marker pinned to a DIFFERENT (older) incarnation uid.
            sup.store.mark_deletion(key, uid="older-uid")
            sup.process_deletion_markers()
            assert sup.store.get(key) is not None  # new job survives
            assert (
                sup.runner.get(replica_name(key, ReplicaType.MASTER, 0)) is None
            ), "unknown-age FINISHED record must be reaped"
            assert (
                sup.runner.get(replica_name(key, ReplicaType.WORKER, 0))
                is not None
            ), "unknown-age ACTIVE replica must be spared"
        finally:
            sup.shutdown()

    def test_gc_key_locks_retires_only_uncontended_dead_keys(self, tmp_path):
        """Locks held by ANOTHER thread survive GC (popping a held lock
        would let a concurrent key_lock mint a second one); dead
        uncontended locks are retired; live keys untouched."""
        import threading

        sup = make_supervisor(tmp_path)
        try:
            rec = sup.reconciler
            lock = rec.key_lock("default/held")
            rec.key_lock("default/dead")
            rec.key_lock("default/live")
            acquired, release = threading.Event(), threading.Event()

            def holder():
                with lock:
                    acquired.set()
                    release.wait(10)

            t = threading.Thread(target=holder)
            t.start()
            assert acquired.wait(5)
            try:
                rec.gc_key_locks(live_keys={"default/live"})
                assert "default/dead" not in rec._key_locks
                assert "default/held" in rec._key_locks  # held elsewhere
                assert "default/live" in rec._key_locks
            finally:
                release.set()
                t.join(timeout=10)
        finally:
            sup.shutdown()

    def test_deletion_marker_for_old_incarnation_spares_new_job(self, tmp_path):
        """A daemon consuming a uid-pinned deletion marker must not kill a
        NEWER incarnation of the same job name (the marker's uid differs
        from the stored job's)."""
        sup = make_supervisor(tmp_path)
        try:
            job = new_job(name="uid-guard", workers=0)
            job.spec.replica_specs[ReplicaType.MASTER].template = (
                ProcessTemplate(command=["sleep", "30"])
            )
            key = sup.submit(job)
            old_uid = "previous-incarnation-uid"
            sup.store.mark_deletion(key, purge=False, uid=old_uid)
            sup.process_deletion_markers()
            assert sup.store.get(key) is not None, (
                "marker for an old incarnation deleted the new job"
            )
            assert key not in sup.store.deletion_markers()  # consumed
            # An unpinned (legacy) or matching-uid marker still deletes.
            sup.store.mark_deletion(key, uid=sup.store.get(key).metadata.uid)
            sup.process_deletion_markers()
            assert sup.store.get(key) is None
        finally:
            sup.shutdown()

    def test_failing_job_backoff(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(
            name="perma-fail",
            workers=0,
            restart_policy=RestartPolicy.ON_FAILURE,
            backoff_limit=1,
        )
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.exit_with", args=["--code", "3"]
        )
        done = sup.run(job, timeout=30)
        assert done.is_failed()
        assert done.get_condition(ConditionType.FAILED).reason == "BackoffLimitExceeded"
        assert done.status.restart_count == 1
        sup.shutdown()

    def test_exit_code_policy_permanent(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="exitcode-perm", workers=0, restart_policy=RestartPolicy.EXIT_CODE)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.exit_with", args=["--code", "7"]
        )
        done = sup.run(job, timeout=30)
        assert done.is_failed()
        assert done.status.restart_count == 0  # 7 is permanent, no retry
        sup.shutdown()

    def test_crash_then_recover(self, tmp_path):
        """Replica fails once with a retryable code, then succeeds."""
        sup = make_supervisor(tmp_path)
        job = new_job(name="flaky", workers=0, restart_policy=RestartPolicy.EXIT_CODE)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.exit_with",
            args=["--code", "130", "--until-restart", "1"],
        )
        done = sup.run(job, timeout=30)
        assert done.is_succeeded()
        assert done.status.restart_count == 1
        sup.shutdown()

    def test_bad_command_fails(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="badcmd", workers=0, restart_policy=RestartPolicy.NEVER)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            command=["/nonexistent/binary"]
        )
        done = sup.run(job, timeout=30)
        assert done.is_failed()
        sup.shutdown()

    def test_logs_written(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="logjob", workers=0)
        sup.run(job, timeout=30)
        logs = list((tmp_path / "state" / "logs").glob("*logjob*"))
        assert logs, "expected a replica log file"
        assert "[noop]" in logs[0].read_text()
        sup.shutdown()

    def test_delete_running_job_kills_processes(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="longrun", workers=0)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.exit_with",
            args=["--sleep", "60", "--code", "0"],
        )
        key = sup.submit(job)
        sup.sync_once()
        handles = sup.runner.list_for_job(key)
        assert len(handles) == 1 and handles[0].pid is not None
        assert sup.delete_job(key)
        assert sup.get(key) is None
        assert sup.runner.list_for_job(key) == []
        sup.shutdown()

    def test_purge_marker_removes_artifacts_after_kill(self, tmp_path):
        """`tpujob delete --purge` from another process: the supervisor must
        purge AFTER terminating replicas, so a live workload can't re-create
        the checkpoint dir behind the purge."""
        sup = make_supervisor(tmp_path)
        job = new_job(name="purgeme", workers=0)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.exit_with",
            args=["--sleep", "60", "--code", "0"],
        )
        key = sup.submit(job)
        sup.sync_once()
        ckpt_dir = sup.state_dir / "checkpoints" / key.replace("/", "_")
        assert ckpt_dir.exists()  # injected at launch
        # Cross-process purge request (what cmd_delete --purge writes).
        marker = sup.state_dir / "jobs" / (key.replace("/", "_") + ".delete")
        marker.write_text("purge")
        sup.process_deletion_markers()
        assert sup.runner.list_for_job(key) == []
        assert not ckpt_dir.exists()
        assert not marker.exists()
        sup.shutdown()


class TestTTLAndPersistence:
    def test_ttl_gc(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="ttl-job", workers=0, ttl_seconds_after_finished=0)
        key = sup.submit(job)
        sup.wait(key, timeout=30)
        # job finished; next sync pass GCs it (ttl=0)
        sup.sync_once()
        assert sup.get(key) is None
        sup.shutdown()

    def test_state_persisted_and_reloaded(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="persist-job", workers=0)
        key = sup.submit(job)
        done = sup.wait(key, timeout=30)
        assert done.is_succeeded()
        sup.shutdown()
        # a fresh supervisor over the same state dir sees the finished job
        sup2 = make_supervisor(tmp_path)
        reloaded = sup2.get(key)
        assert reloaded is not None
        assert reloaded.is_succeeded()
        assert reloaded.metadata.uid == done.metadata.uid
        sup2.shutdown()

    def test_corrupt_state_file_skipped(self, tmp_path):
        d = tmp_path / "jobs"
        d.mkdir(parents=True)
        (d / "default_bad.json").write_text("{not json")
        store = JobStore(persist_dir=d)
        assert store.list() == []


class TestScale:
    def test_scale_requires_elastic(self, tmp_path):
        sup = make_supervisor(tmp_path)
        key = sup.submit(new_job(name="noelastic", workers=1))
        with pytest.raises(ValidationError, match="elastic"):
            sup.scale(key, 2)
        sup.shutdown()

    def test_scale_bounds_checked(self, tmp_path):
        sup = make_supervisor(tmp_path)
        key = sup.submit(
            new_job(
                name="el",
                workers=2,
                elastic=ElasticPolicy(min_replicas=1, max_replicas=3),
            )
        )
        with pytest.raises(ValidationError, match="outside"):
            sup.scale(key, 5)
        sup.shutdown()

    def test_scale_marker_processed(self, tmp_path):
        """Cross-process `tpujob scale` marker → supervisor resizes the job."""
        sup = make_supervisor(tmp_path)
        job = new_job(
            name="el3",
            workers=1,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=3, max_restarts=5),
        )
        key = sup.submit(job)
        marker = sup.state_dir / "jobs" / (key.replace("/", "_") + ".scale")
        marker.write_text("3")
        sup.process_scale_markers()
        assert not marker.exists()
        assert sup.get(key).spec.replica_specs[ReplicaType.WORKER].replicas == 3
        # invalid request: cleared and recorded, not raised
        marker.write_text("9")
        sup.process_scale_markers()
        assert not marker.exists()
        assert sup.get(key).spec.replica_specs[ReplicaType.WORKER].replicas == 3
        # claim-by-rename consumes the marker; a fresh request written at
        # the marker path afterwards is a new file and is NOT lost
        marker.write_text("2")
        assert sup.store.take_scale_markers() == [(key, 2)]
        assert not marker.exists()
        assert sup.store.take_scale_markers() == []
        sup.shutdown()

    def test_scale_restarts_gang_with_new_world(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(
            name="el2",
            workers=2,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=3, max_restarts=5),
        )
        for rs in job.spec.replica_specs.values():
            rs.template = ProcessTemplate(
                module="pytorch_operator_tpu.workloads.exit_with",
                args=["--sleep", "60", "--code", "0"],
            )
        key = sup.submit(job)
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 3
        sup.scale(key, 1)
        sup.sync_once()
        handles = sup.runner.list_for_job(key)
        assert len(handles) == 2  # master + 1 worker
        job2 = sup.get(key)
        assert job2.status.restart_count == 1
        # env reflects the new world size
        sup.runner.sync()
        sup.delete_job(key)
        sup.shutdown()


class TestMetricsRender:
    def test_prometheus_text(self, tmp_path):
        sup = make_supervisor(tmp_path)
        sup.submit(new_job(name="m1", workers=0))
        sup.sync_once()
        text = sup.metrics.render_text()
        assert "# TYPE tpujob_jobs_created_total counter" in text
        assert "tpujob_jobs_created_total 1" in text
        sup.shutdown()


class TestSignalDeath:
    def test_sigkill_is_retryable_under_exit_code_policy(self, tmp_path):
        """Popen reports signal death as -N; the runner must normalize to
        128+N so ExitCode policy treats preemption (SIGKILL) as retryable."""
        import os
        import signal as _signal

        sup = make_supervisor(tmp_path)
        job = new_job(name="preempt", workers=0, restart_policy=RestartPolicy.EXIT_CODE)
        job.spec.replica_specs[ReplicaType.MASTER].template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.exit_with",
            args=["--sleep", "30", "--code", "0"],
        )
        key = sup.submit(job)
        sup.sync_once()
        h = sup.runner.list_for_job(key)[0]
        # Preemption kills the whole replica group (wrapper + workload);
        # killing only the wrapper is a different case — the replica
        # survives and stays RUNNING (tests/test_adoption.py).
        os.killpg(h.pid, _signal.SIGKILL)
        deadline = time.time() + 20
        while time.time() < deadline:
            sup.sync_once()
            j = sup.get(key)
            if j.status.restart_count >= 1:
                break
            time.sleep(0.05)
        j = sup.get(key)
        assert not j.is_failed(), "SIGKILL must be retryable, not a permanent failure"
        assert j.status.restart_count == 1
        sup.delete_job(key)
        sup.shutdown()


class TestAutoPort:
    def test_omitted_port_is_auto_allocated(self, tmp_path):
        sup = make_supervisor(tmp_path)
        job = new_job(name="auto-port", workers=0)
        assert job.spec.port == 23456  # defaulted by fixture
        job.spec.port = None  # user omitted it
        key = sup.submit(job)
        sup.sync_once()
        j = sup.get(key)
        assert j.spec.port != 23456 and 1024 < j.spec.port <= 65535
        sup.delete_job(key)
        sup.shutdown()

    def test_explicit_default_port_honored(self, tmp_path):
        sup = make_supervisor(tmp_path)
        # Build undefaulted so the explicit port is set BEFORE defaulting
        # (defaulting is what distinguishes omitted from explicit).
        job = new_job(name="explicit-port", workers=0, defaulted=False)
        job.spec.port = 23456  # explicitly set by user
        key = sup.submit(job)
        sup.sync_once()
        assert sup.get(key).spec.port == 23456
        sup.delete_job(key)
        sup.shutdown()
