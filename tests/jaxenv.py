"""Import this FIRST in any test module that uses jax in-process.

Applies the CPU platform + gloo collectives via jax.config (the env var
alone is overridden by this environment's site customization — see
runtime/backend.py). Kept out of conftest so pure control-plane test runs
never pay the jax import.
"""

from pytorch_operator_tpu.runtime.backend import setup_backend

setup_backend("cpu")
