"""Request-level serve-plane tracing + SLO burn-rate accounting.

The observability PR's tier-1 pins:

- a trace context rides every request frame (rid + origin ts + a
  DETERMINISTIC parent span id, so replay cannot fork a waterfall) and
  is a few bytes of dead weight when tracing is off;
- the serve path emits one span per hop — enqueue, claim, dispatch,
  ring/spool transit, slot wait, decode, respond, publish — and
  ``tpujob trace --request`` renders them as one causal waterfall;
- chaos keeps the waterfall coherent: a replica killed mid-request
  re-routes with a visible ``reroute`` hop and exactly ONE terminal
  ``publish`` span; a recovered batch replay does not duplicate
  request spans — on the file spool and the shm-ring tier both;
- zero overhead when disabled: the serve path emits exactly zero span
  records without ``TPUJOB_TRACE_DIR`` (the bench_smoke pin extended
  from the step path);
- ``BurnAccount`` error-budget math, the ``slo_burn`` detector (tail
  semantics), and the live pending -> firing -> resolved lifecycle
  with offline ``tpujob why`` parity;
- per-lane RouterIOCounters stay monotonic across job retirement (the
  Prometheus counter fold reads them as totals);
- ``prearm_rings`` creates the ring pair at replica spawn so first
  dispatch never pays ring creation.
"""

from __future__ import annotations

import time

import pytest

from pytorch_operator_tpu import obs
from pytorch_operator_tpu.api.types import ReplicaType
from pytorch_operator_tpu.obs import trace as obs_trace
from pytorch_operator_tpu.obs.rules import (
    DEFAULT_THRESHOLDS,
    Thresholds,
    detect_slo_burn,
)
from pytorch_operator_tpu.serving import Spool, make_request
from pytorch_operator_tpu.serving.router import (
    PER_LANE_KEYS,
    ServeRouter,
    front_spool_dir,
    replica_spool_dir,
    serve_root_dir,
)
from pytorch_operator_tpu.serving.shmring import (
    EngineRingPort,
    EngineTransport,
    prearm_rings,
)
from pytorch_operator_tpu.serving.slo import SLO, BurnAccount
from pytorch_operator_tpu.workloads import serveplane_bench

pytestmark = pytest.mark.bench_smoke


@pytest.fixture
def traced_dir(tmp_path, monkeypatch):
    """Arm the process tracer at a tmp dir; disarm + re-cache on exit."""
    d = tmp_path / "trace"
    monkeypatch.setenv(obs_trace.ENV_VAR, str(d))
    obs_trace.reset_tracer()
    yield d
    monkeypatch.delenv(obs_trace.ENV_VAR, raising=False)
    obs_trace.reset_tracer()


class _Handle:
    def __init__(self, rtype=ReplicaType.MASTER, index=0, active=True):
        self.replica_type = rtype
        self.index = index
        self._active = active

    def is_active(self):
        return self._active


def _handles(n):
    out = [_Handle(ReplicaType.MASTER, 0)]
    out += [_Handle(ReplicaType.WORKER, i) for i in range(n - 1)]
    return out


def _job(replicas=1, transport="spool", **kw):
    return serveplane_bench._make_serve_job(
        "svc", replicas, slots=4, tpot_ms=10.0, idle_timeout=0.0,
        max_queue_depth=kw.get("max_queue_depth", 0),
        deadline_s=kw.get("deadline_s", 0.0),
        retry_limit=kw.get("retry_limit", 3),
        transport=transport,
        slo_target=kw.get("slo_target", 0.0),
        burn_window_s=kw.get("burn_window_s", 0.0),
    )


def _flush_spans():
    rec = obs_trace.tracer()
    if rec is not None:
        rec.flush()


def _spans(trace_dir, name=None, rid=None):
    out = []
    for p in obs_trace.span_files(trace_dir):
        for e in obs_trace.load_span_file(p):
            if e.get("ph") != "X":
                continue
            if name is not None and e.get("name") != name:
                continue
            if rid is not None and (e.get("args") or {}).get("rid") != rid:
                continue
            out.append(e)
    return out


# ---- trace context on the frame ----


class TestTraceContext:
    def test_request_carries_deterministic_context(self):
        rec = make_request(prompt_len=2, max_new_tokens=4)
        tctx = rec["tctx"]
        assert abs(tctx["o"] - rec["submit_time"]) < 1e-5
        # Deterministic parent span id: the same rid always derives the
        # same id, so a replayed frame cannot fork the waterfall.
        import zlib

        assert tctx["p"] == "%08x" % (
            zlib.crc32(rec["id"].encode()) & 0xFFFFFFFF
        )

    def test_dispatch_stamps_transit_time(self, tmp_path):
        """The router stamps ``tx`` (wall clock — the engine lives in
        another process) on a FRESH dict, leaving the claimed frame's
        own context unmodified."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(transport="shmring")
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        front.submit(prompt_len=2, max_new_tokens=4)
        t0 = time.time()
        router.tick(key, job, _handles(1), {})
        eng = EngineRingPort.attach(
            replica_spool_dir(serve_root_dir(state), key, "Master", 0)
        )
        (req,) = eng.recv()
        assert req["tctx"]["tx"] >= t0 - 0.001
        assert "o" in req["tctx"] and "p" in req["tctx"]
        eng.close()
        router.close()


# ---- zero overhead when disabled ----


class TestZeroOverheadServePath:
    def test_serve_path_emits_no_spans_without_trace_dir(self, tmp_path):
        """The bench_smoke zero-overhead pin, serve-path edition: a
        full request lifecycle — enqueue, claim, dispatch, engine poll,
        respond, publish — emits exactly ZERO span records when tracing
        is disabled."""
        assert obs_trace.tracer() is None
        before = obs_trace.records_emitted()
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(transport="shmring")
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.submit(prompt_len=2, max_new_tokens=4)
        front.enqueue_batch(
            [make_request(prompt_len=2, max_new_tokens=4) for _ in range(3)]
        )
        router.tick(key, job, _handles(1), {})
        et = EngineTransport(
            replica_spool_dir(serve_root_dir(state), key, "Master", 0),
            "shmring",
        )
        recs, _ = et.poll_requests(8)
        assert recs
        for r in recs:
            et.respond(r["id"], {"id": r["id"], "tokens": [1], "ttft_ms": 1.0})
        time.sleep(0.02)
        router.tick(key, job, _handles(1), {})
        assert front.has_response(rid)
        assert obs_trace.records_emitted() == before
        et.close()
        router.close()


# ---- the waterfall, both transports ----


class TestWaterfall:
    @pytest.mark.parametrize("transport", ["spool", "shmring"])
    def test_full_hop_chain_one_publish(self, tmp_path, traced_dir, transport):
        """One traced request crosses >= 5 distinct hops, every span
        carries the rid, and the terminal ``publish`` span exists
        exactly once."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(transport=transport)
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.enqueue(make_request(prompt_len=2, max_new_tokens=4))
        router.tick(key, job, _handles(1), {})
        et = EngineTransport(
            replica_spool_dir(serve_root_dir(state), key, "Master", 0),
            transport,
        )
        (req,), _ = et.poll_requests(8)
        assert req["id"] == rid
        et.respond(rid, {"id": rid, "tokens": [1], "ttft_ms": 1.0})
        time.sleep(0.02)
        router.tick(key, job, _handles(1), {})
        assert front.has_response(rid)
        et.close()
        router.close()
        _flush_spans()

        spans = _spans(traced_dir, rid=rid)
        names = [s["name"] for s in spans]
        transit = "ring_transit" if transport == "shmring" else "spool_transit"
        for hop in ("enqueue", "claim", "dispatch", transit, "publish"):
            assert hop in names, (hop, names)
        assert len(set(names)) >= 5
        assert names.count("publish") == 1
        assert names.count("enqueue") == 1
        (pub,) = [s for s in spans if s["name"] == "publish"]
        assert pub["args"]["outcome"] == "ok"

    def test_cli_waterfall_renders_hops_in_clock_order(self, tmp_path, traced_dir):
        from pytorch_operator_tpu.client.cli import _render_request_waterfall
        from pytorch_operator_tpu.obs.trace import merge_trace_files, span_files

        state = tmp_path / "state"
        key = "default/svc"
        job = _job(transport="shmring")
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.enqueue(make_request(prompt_len=2, max_new_tokens=4))
        router.tick(key, job, _handles(1), {})
        et = EngineTransport(
            replica_spool_dir(serve_root_dir(state), key, "Master", 0),
            "shmring",
        )
        (req,), _ = et.poll_requests(8)
        et.respond(rid, {"id": rid, "tokens": [1], "ttft_ms": 1.0})
        time.sleep(0.02)
        router.tick(key, job, _handles(1), {})
        et.close()
        router.close()
        _flush_spans()

        doc = merge_trace_files(span_files(traced_dir))
        text = _render_request_waterfall(doc, rid)
        assert text is not None
        lines = text.splitlines()
        assert rid in lines[0]
        hop_lines = lines[1:]
        assert len(hop_lines) >= 5
        # Offsets are monotonic: the waterfall reads top-to-bottom in
        # causal order on one clock axis.
        offs = [float(ln.split("ms")[0]) for ln in hop_lines]
        assert offs == sorted(offs)
        assert offs[0] == 0.0
        assert _render_request_waterfall(doc, "no-such-rid") is None


# ---- chaos keeps the waterfall coherent ----


class TestChaosPropagation:
    @pytest.mark.parametrize("transport", ["spool", "shmring"])
    def test_kill_reroute_one_coherent_waterfall(
        self, tmp_path, traced_dir, transport
    ):
        """A replica dies after consuming the request: the re-route to
        the survivor appears as a ``reroute`` hop and the waterfall
        still ends in exactly ONE terminal publish span."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(replicas=2, transport=transport)
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.enqueue(make_request(prompt_len=2, max_new_tokens=4))
        handles = _handles(2)
        router.tick(key, job, handles, {})

        # Find the replica that got it, consume there, then kill it.
        serve_root = serve_root_dir(state)
        victim = None
        ports = []
        for h in handles:
            et = EngineTransport(
                replica_spool_dir(serve_root, key, h.replica_type.value, h.index),
                transport,
            )
            ports.append((h, et))
            recs, _ = et.poll_requests(8)
            if recs:
                victim = h
        assert victim is not None
        victim._active = False

        survivor = next(h for h in handles if h is not victim)
        surv_et = next(et for h, et in ports if h is survivor)
        redelivered = None
        deadline = time.monotonic() + 5.0
        while redelivered is None and time.monotonic() < deadline:
            router.tick(key, job, handles, {})
            recs, _ = surv_et.poll_requests(8)
            for r in recs:
                if r["id"] == rid:
                    redelivered = r
            time.sleep(0.02)
        assert redelivered is not None
        surv_et.respond(rid, {"id": rid, "tokens": [5], "ttft_ms": 2.0})
        deadline = time.monotonic() + 5.0
        while not front.has_response(rid) and time.monotonic() < deadline:
            router.tick(key, job, handles, {})
            time.sleep(0.02)
        assert front.has_response(rid)
        for _, et in ports:
            et.close()
        router.close()
        _flush_spans()

        spans = _spans(traced_dir, rid=rid)
        names = [s["name"] for s in spans]
        assert names.count("publish") == 1, names
        assert names.count("reroute") == 1, names
        assert names.count("enqueue") == 1, names
        assert names.count("dispatch") == 2, names  # original + re-drive
        (rr,) = [s for s in spans if s["name"] == "reroute"]
        assert rr["args"]["attempts"] >= 1

    def test_recovered_batch_replay_no_duplicate_spans(self, tmp_path, traced_dir):
        """Engine-restart replay: recover_claimed() re-queues a claimed
        batch; the re-claim must not re-emit client enqueue spans, and
        the already-answered record keeps its single span set."""
        sp = Spool(tmp_path / "spool")
        recs = [make_request(prompt_len=2, max_new_tokens=2) for _ in range(3)]
        rids = sp.enqueue_batch(recs)
        got = sp.claim(8)
        assert len(got) == 3
        sp.respond(rids[0], {"id": rids[0], "tokens": [1]})
        assert sp.recover_claimed() >= 1
        again = sp.claim(8)
        assert sorted(r["id"] for r in again) == sorted(rids[1:])
        _flush_spans()
        enq = _spans(traced_dir, name="enqueue")
        assert sorted((e["args"] or {})["rid"] for e in enq) == sorted(rids)
        assert len(enq) == 3  # one per client write, replay added none

    def test_router_spill_copy_does_not_reemit_enqueue(self, tmp_path, traced_dir):
        """The router's file-spill dispatch reuses Spool.enqueue for
        the replica spool; those frames carry ``attempts`` and must not
        masquerade as client enqueues."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(transport="spool")
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.enqueue(make_request(prompt_len=2, max_new_tokens=4))
        router.tick(key, job, _handles(1), {})  # dispatch = spool spill
        router.close()
        _flush_spans()
        enq = _spans(traced_dir, name="enqueue", rid=rid)
        assert len(enq) == 1


# ---- burn accounting ----


class TestBurnAccount:
    def test_burn_math_and_window_decay(self):
        acc = BurnAccount(target=0.99, fast_window_s=1.0)
        assert acc.fast_label == "1s"
        assert [w for w, _ in acc.windows] == ["1s", "5m"]
        t = 1000.0
        for i in range(10):
            acc.record(t + i * 0.1, bad=(i % 2 == 0))  # 5 bad / 10
        burn = acc.burn(t + 1.0)
        assert burn["1s"] == pytest.approx(50.0, rel=0.01)
        # After the fast window passes the events, its burn decays to 0
        # while the 5m window still sees them.
        later = acc.burn(t + 3.0)
        assert later["1s"] == 0.0
        assert later["5m"] > 0.0

    def test_all_good_is_zero_and_empty_is_zero(self):
        acc = BurnAccount(target=0.99, fast_window_s=30.0)
        assert acc.fast_label == "30s"
        assert acc.burn(100.0) == {"30s": 0.0, "5m": 0.0}
        acc.record(100.0, bad=False)
        assert acc.burn(100.5)["30s"] == 0.0

    def test_slo_from_policy_resolves_target_and_window(self):
        job = _job(slo_target=0.999, burn_window_s=5.0, deadline_s=1.0)
        slo = SLO.from_policy(job.spec.serving)
        assert slo.target == 0.999
        assert slo.burn_window_s == 5.0
        # Unset (0.0) falls back to the defaults.
        slo2 = SLO.from_policy(_job().spec.serving)
        assert slo2.target == 0.99
        assert slo2.burn_window_s == 30.0

    def test_router_tick_surfaces_burn_and_spills(self, tmp_path):
        """Overload against a depth-1 bar: sheds burn the budget and
        the tick summary carries burn + per-window breakdown."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(max_queue_depth=1, slo_target=0.99, burn_window_s=30.0)
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        for _ in range(6):
            front.submit(prompt_len=2, max_new_tokens=4)
        summary = router.tick(key, job, _handles(1), {})
        assert summary["shed"] == 5
        assert summary["burn"] > 1.0
        assert set(summary["burn_by_window"]) == {"30s", "5m"}
        assert summary["spills"] == 0
        router.close()


# ---- the slo_burn rule: offline detector + live lifecycle ----


def _serve_rec(ts, burn, shed=0):
    return {
        "replica": "router", "ts": ts, "aligned_ts": ts,
        "burn": burn, "shed": shed, "queue_depth": 0.0,
    }


class _View:
    window_s = None

    def __init__(self, recs):
        self.records = {"serve": recs}

    def in_window(self, ts):
        return True

    def find_event(self, *reasons):
        return None


class TestSloBurnRule:
    def test_fires_on_sustained_tail_only(self):
        hot = [_serve_rec(float(i), 3.0, shed=2) for i in range(4)]
        (f,) = detect_slo_burn(_View(hot), DEFAULT_THRESHOLDS)
        assert f.rule == "slo_burn"
        assert f.severity == "critical"  # 3.0 >= 2x threshold
        assert f.metrics["burn_peak"] == 3.0
        # Tail semantics: a past episode followed by recovery is NOT a
        # live finding (the alert log owns history).
        cooled = hot + [_serve_rec(10.0 + i, 0.0) for i in range(3)]
        assert detect_slo_burn(_View(cooled), DEFAULT_THRESHOLDS) == []
        # Below threshold never fires.
        mild = [_serve_rec(float(i), 0.4) for i in range(4)]
        assert detect_slo_burn(_View(mild), DEFAULT_THRESHOLDS) == []

    def test_threshold_overrides(self):
        th = Thresholds(slo_burn_rate=5.0, slo_burn_samples=2)
        recs = [_serve_rec(0.0, 6.0), _serve_rec(1.0, 5.5)]
        (f,) = detect_slo_burn(_View(recs), th)
        assert f.metrics["threshold"] == 5.0
        assert detect_slo_burn(_View(recs), Thresholds(slo_burn_rate=7.0)) == []

    def test_live_lifecycle_and_offline_parity(self, tmp_path):
        """pending -> firing -> resolved through the real WatchEngine,
        transitions on disk; replaying the same records offline
        (ingest_record is the parity contract) reproduces the story."""
        from pytorch_operator_tpu.obs.watch import WatchEngine, load_alert_log

        state = tmp_path / "state"
        state.mkdir()
        key = "default/svc"
        job = _job()
        # Configure hysteresis via the spec block the engine resolves.
        from pytorch_operator_tpu.api.types import AlertPolicy, ObservabilityPolicy

        job.spec.observability = ObservabilityPolicy(
            alerts=AlertPolicy(for_s=1.0, clear_s=1.0)
        )
        eng = WatchEngine(state, host="h")
        t0 = 1000.0
        for i in range(4):
            eng.ingest_record(key, "router", "serve", _serve_rec(t0 + i, 4.0, shed=3))
        alerts = eng.evaluate(key, job, now=t0 + 3.0)
        assert [a.state for a in alerts if a.rule == "slo_burn"] == ["pending"]
        # Still hot past for_s: fires.
        eng.ingest_record(key, "router", "serve", _serve_rec(t0 + 4.5, 4.0, shed=3))
        alerts = eng.evaluate(key, job, now=t0 + 4.5)
        assert [a.state for a in alerts if a.rule == "slo_burn"] == ["firing"]
        # Burn decays: the tail goes quiet. Within clear_s the alert
        # keeps firing (hysteresis); past it, it resolves (logged).
        for i in range(3):
            eng.ingest_record(key, "router", "serve", _serve_rec(t0 + 5.0 + i, 0.0))
        assert [
            a for a in eng.evaluate(key, job, now=t0 + 5.2)
            if a.rule == "slo_burn" and a.state == "firing"
        ]
        alerts = eng.evaluate(key, job, now=t0 + 9.0)
        assert not [a for a in alerts if a.rule == "slo_burn"]
        states = [
            r["state"]
            for r in load_alert_log(state, key)
            if r["rule"] == "slo_burn"
        ]
        assert states == ["firing", "resolved"]


# ---- TTFT attribution ----


class TestTTFTAttribution:
    def _span(self, name, dur_ms, rid="r1"):
        return {
            "ph": "X", "name": name, "cat": "serve",
            "ts": 0, "dur": int(dur_ms * 1000), "args": {"rid": rid},
        }

    def test_dominant_hop_and_render(self):
        from pytorch_operator_tpu.obs.analyze import (
            render_report,
            ttft_attribution,
        )

        spans = [
            self._span("claim", 2.0),
            self._span("dispatch", 1.0),
            self._span("ring_transit", 0.5),
            self._span("slot_wait", 40.0),
            self._span("decode", 10.0),
            self._span("claim", 3.0, rid="r2"),
        ]
        att = ttft_attribution(spans)
        assert att["dominant"] == "slot_wait"
        assert att["requests"] == 2
        assert att["hops"]["queue_wait"]["n"] == 2
        assert att["hops"]["transit"]["total_ms"] == 0.5
        report = {
            "job": "default/svc", "replicas": {}, "events": 0, "spans": 6,
            "findings": [], "alerts": [], "ttft_attribution": att,
        }
        text = render_report(report)
        assert "TTFT ATTRIBUTION" in text
        assert "dominant hop: slot_wait" in text

    def test_none_without_serve_spans(self):
        from pytorch_operator_tpu.obs.analyze import ttft_attribution

        assert ttft_attribution([]) is None
        assert ttft_attribution(
            [{"ph": "X", "name": "step", "cat": "train", "ts": 0, "dur": 5}]
        ) is None


# ---- per-lane counters + ring pre-arm ----


class TestLaneCountersAndPrearm:
    def test_lane_io_monotonic_across_retire(self, tmp_path):
        state = tmp_path / "state"
        key = "default/svc"
        job = _job(transport="shmring")
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        front.submit(prompt_len=2, max_new_tokens=4)
        router.tick(key, job, _handles(1), {})
        lanes = router.lane_io_snapshot()
        assert lanes[0]["ring_sends"] == 1
        assert set(lanes[0]) == set(PER_LANE_KEYS)
        router.retire_job(key)
        after = router.lane_io_snapshot()
        assert after[0]["ring_sends"] == 1  # totals survive retirement
        router.close()

    def test_metrics_registry_has_router_lane_counters(self):
        from pytorch_operator_tpu.controller.metrics import MetricsRegistry

        m = MetricsRegistry()
        assert set(m.router_lane_io) == set(PER_LANE_KEYS)
        m.router_lane_io["ring_sends"].inc(3, lane="0")
        text = m.render_text()
        assert 'tpujob_router_ring_sends_total{lane="0"} 3' in text
        m.slo_burn_rate.set(1.5, job="default/svc", window="30s")
        assert "tpujob_slo_burn_rate" in m.render_text()

    def test_prearm_creates_ring_pair_once(self, tmp_path):
        root = tmp_path / "spool"
        assert prearm_rings(root) is True
        assert (root / "req.ring").exists()
        assert (root / "resp.ring").exists()
        assert prearm_rings(root) is False  # idempotent
        # The engine can attach immediately — no first-dispatch stall.
        port = EngineRingPort.attach(root)
        assert port is not None
        port.close()
