"""Chunked large-vocab cross-entropy vs the dense reference."""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401

from pytorch_operator_tpu.ops.chunked_xent import chunked_softmax_xent


def _dense_ref(hidden, w, labels):
    import jax.numpy as jnp
    import optax

    logits = hidden.astype(jnp.float32) @ w.astype(jnp.float32)
    return optax.softmax_cross_entropy_with_integer_labels(logits, labels)


def _rand(n, d, v, seed=0, dtype=np.float32):
    rng = np.random.default_rng(seed)
    hidden = rng.standard_normal((n, d)).astype(dtype)
    w = (rng.standard_normal((d, v)) * 0.05).astype(dtype)
    labels = rng.integers(0, v, n).astype(np.int32)
    return hidden, w, labels


class TestForward:
    @pytest.mark.parametrize("chunk", [7, 32, 1000])
    def test_matches_dense(self, chunk):
        import jax.numpy as jnp

        hidden, w, labels = _rand(12, 16, 96)
        out = chunked_softmax_xent(
            jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels), chunk=chunk
        )
        ref = _dense_ref(jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    @pytest.mark.parametrize("v,chunk", [(97, 64), (101, 25), (100, 100)])
    def test_non_divisible_vocab(self, v, chunk):
        """Prime/non-divisible V exercises the clamped, masked tail chunk."""
        import jax.numpy as jnp

        hidden, w, labels = _rand(9, 8, v, seed=7)
        out = chunked_softmax_xent(
            jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels), chunk=chunk
        )
        ref = _dense_ref(jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_out_of_range_labels_clamp_deterministically(self):
        """Labels outside [0, V) clamp to the range edges — a defined,
        finite behavior (optax's dense path yields NaN there; the old
        chunked behavior silently returned plain lse)."""
        import jax.numpy as jnp
        import optax

        from pytorch_operator_tpu.ops.chunked_xent import chunked_softmax_xent

        hidden, w, _ = _rand(16, 8, 50)
        labels = np.array([-1, -100, 0, 49, 50, 99, 7, 3] * 2, np.int32)
        got = chunked_softmax_xent(
            jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels), chunk=16
        )
        assert np.isfinite(np.asarray(got)).all()
        logits = jnp.asarray(hidden) @ jnp.asarray(w)
        ref = optax.softmax_cross_entropy_with_integer_labels(
            logits, jnp.clip(jnp.asarray(labels), 0, 49)
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(ref), rtol=1e-5, atol=1e-5
        )

    def test_bf16_hidden(self):
        import jax.numpy as jnp

        hidden, w, labels = _rand(8, 16, 64)
        out = chunked_softmax_xent(
            jnp.asarray(hidden, jnp.bfloat16), jnp.asarray(w), jnp.asarray(labels),
            chunk=16,
        )
        ref = _dense_ref(
            jnp.asarray(hidden, jnp.bfloat16), jnp.asarray(w), jnp.asarray(labels)
        )
        assert out.dtype == jnp.float32
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-2, atol=2e-2)


class TestLlamaIntegration:
    @pytest.mark.slow
    def test_chunked_llama_matches_dense_loss(self):
        """End-to-end through the shared trainer: the chunked path's loss and
        first train step must agree with the dense path."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import optax

        from pytorch_operator_tpu.models import llama as llama_lib
        from pytorch_operator_tpu.parallel import make_mesh
        from pytorch_operator_tpu.workloads.trainer import (
            init_sharded_train_state,
            make_lm_train_step,
        )

        tokens = jnp.asarray(
            np.random.default_rng(0).integers(0, 256, (4, 16)), jnp.int32
        )
        mesh = make_mesh("dp=8")
        losses = {}
        for impl in ("dense", "chunked"):
            cfg = llama_lib.llama_tiny(xent_impl=impl)
            model = llama_lib.Llama(cfg)
            tx = optax.adamw(1e-3)
            state, _ = init_sharded_train_state(
                lambda k: model.init(k, jnp.zeros((1, 16), jnp.int32)), tx, mesh
            )
            with mesh:
                step = make_lm_train_step(model, tx, mesh)
                _, loss = step(state, tokens)
            losses[impl] = float(loss)
        assert losses["chunked"] == pytest.approx(losses["dense"], rel=1e-4)


class TestVocabStats:
    """chunked_vocab_stats: the combinable partial-stat form behind the
    pipeline's vocab-parallel loss tail."""

    @pytest.mark.parametrize("chunk", [16, 23, 64])
    def test_sharded_stats_combine_to_dense_loss_and_grads(self, chunk):
        """Split the head into 4 column shards, compute per-shard stats
        (multi-sub-chunk streaming when chunk < V/4), combine with the
        documented max/sumexp/target reduction: loss AND grads must
        equal the dense reference."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.ops.chunked_xent import chunked_vocab_stats

        n, d, v, shards = 12, 8, 64, 4
        hidden, w, labels = _rand(n, d, v, seed=3)
        vl = v // shards

        def sharded_loss(hidden, w):
            ms, ss, ls = [], [], []
            for i in range(shards):
                m, s, lab = chunked_vocab_stats(
                    jnp.asarray(hidden),
                    jnp.asarray(w[:, i * vl : (i + 1) * vl]),
                    jnp.asarray(labels),
                    chunk=chunk,
                    col_offset=i * vl,
                )
                ms.append(m), ss.append(s), ls.append(lab)
            m_g = jnp.max(jnp.stack(ms), 0)
            se = sum(s * jnp.exp(m - m_g) for m, s in zip(ms, ss))
            tgt = sum(ls)
            return (m_g + jnp.log(se) - tgt).mean()

        def dense_loss(hidden, w):
            return _dense_ref(
                jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels)
            ).mean()

        got, (dh, dw) = jax.value_and_grad(sharded_loss, argnums=(0, 1))(
            hidden, w
        )
        ref, (rdh, rdw) = jax.value_and_grad(dense_loss, argnums=(0, 1))(
            hidden, w
        )
        assert float(got) == pytest.approx(float(ref), rel=1e-5)
        np.testing.assert_allclose(np.asarray(dh), np.asarray(rdh), rtol=1e-4, atol=1e-6)
        np.testing.assert_allclose(np.asarray(dw), np.asarray(rdw), rtol=1e-4, atol=1e-6)

    def test_out_of_shard_labels_contribute_zero(self):
        import jax.numpy as jnp

        from pytorch_operator_tpu.ops.chunked_xent import chunked_vocab_stats

        hidden, w, _ = _rand(6, 8, 32, seed=4)
        # All labels live OUTSIDE this shard's [64, 96) column range.
        labels = np.arange(6, dtype=np.int32)
        _, _, lab = chunked_vocab_stats(
            jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels),
            chunk=16, col_offset=64,
        )
        np.testing.assert_array_equal(np.asarray(lab), np.zeros(6, np.float32))


class TestGrads:
    @pytest.mark.parametrize("v,chunk", [(80, 32), (97, 64)])
    def test_grads_match_dense(self, v, chunk):
        import jax
        import jax.numpy as jnp

        hidden, w, labels = _rand(10, 12, v, seed=3)
        hj, wj, lj = jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels)

        def loss_chunked(h, w):
            return chunked_softmax_xent(h, w, lj, chunk=chunk).mean()

        def loss_dense(h, w):
            return _dense_ref(h, w, lj).mean()

        gc = jax.grad(loss_chunked, argnums=(0, 1))(hj, wj)
        gd = jax.grad(loss_dense, argnums=(0, 1))(hj, wj)
        np.testing.assert_allclose(np.asarray(gc[0]), np.asarray(gd[0]), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gc[1]), np.asarray(gd[1]), rtol=1e-4, atol=1e-5)

    def test_jit_and_value_grad(self):
        import jax
        import jax.numpy as jnp

        hidden, w, labels = _rand(6, 8, 40, seed=5)
        hj, wj, lj = jnp.asarray(hidden), jnp.asarray(w), jnp.asarray(labels)

        @jax.jit
        def f(h, w):
            return chunked_softmax_xent(h, w, lj, chunk=10).mean()

        val, grads = jax.value_and_grad(f, argnums=(0, 1))(hj, wj)
        ref = _dense_ref(hj, wj, lj).mean()
        np.testing.assert_allclose(float(val), float(ref), rtol=1e-5)
        assert grads[0].shape == hj.shape and grads[1].shape == wj.shape
