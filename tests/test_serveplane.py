"""Serve plane end-to-end: router + SLO + bench smoke lane.

The serve-plane PR's tier-1 pins, all through the REAL stack (a
Supervisor spawning ``serve_stub`` replicas, the supervisor-hosted
router doing admission / dispatch / retry-on-death / exactly-once
publication):

- bench smoke lane (``-m bench_smoke``): every response is
  SLO-accounted (``accounted == offered`` in every cell), shed rate is
  ZERO when healthy under capacity, duplicates and lost are ZERO, and
  a fleet with no serving jobs costs the router NOTHING — zero ticks,
  no ``<state>/serve`` dir, sub-millisecond idle passes;
- chaos through the ROUTER path: ``kill_replica`` mid-request
  re-routes the dead replica's in-flight requests and still answers
  every submit exactly once; ``fail_engine_step`` surfaces error
  responses for the aborted batch, exactly once;
- the overload contract: a request shed by ``spec.serving.slo``
  carries the explicit ``overload`` marker;
- router-restart dedup: a recovered front claim whose response a
  previous life already collected is re-adopted and published once;
- ``tpujob why`` cites replica death as the cause of a serve-plane
  TTFT spike (queue_growth / batch_size_collapse findings carry the
  coinciding death event as evidence).
"""

from __future__ import annotations

import json
import os
import time

import pytest

from pytorch_operator_tpu.api.types import ReplicaType
from pytorch_operator_tpu.controller.store import key_to_fs
from pytorch_operator_tpu.serving import Spool
from pytorch_operator_tpu.serving.router import (
    ServeRouter,
    front_spool_dir,
    replica_spool_dir,
    serve_root_dir,
)
from pytorch_operator_tpu.workloads import serveplane_bench

pytestmark = pytest.mark.bench_smoke


# ---- bench smoke lane ----


@pytest.fixture(scope="module")
def smoke_result(tmp_path_factory):
    td = tmp_path_factory.mktemp("serveplane")
    # Small but real: subprocess replicas, the live router, open-loop
    # Poisson arrivals — sized UNDER capacity so the healthy cells
    # must not shed at all.
    return serveplane_bench.run(
        replica_cells=(1, 2),
        scenarios=("healthy",),
        rate=20.0,
        duration=1.5,
        slots=4,
        tpot_ms=10.0,
        max_new_tokens=4,
        max_queue_depth=64,
        deadline_s=5.0,
        idle_timeout=2.5,
        idle_jobs=6,
        idle_passes=10,
        work_dir=str(td),
        log=lambda *_: None,
    )


class TestServePlaneSmoke:
    def test_every_response_slo_accounted(self, smoke_result):
        # THE closure pin: every submitted request got exactly one
        # response and every response landed in exactly one SLO bucket.
        for c in smoke_result["cells"]:
            assert c["offered"] > 0, c
            assert c["accounted"] == c["offered"], c
            assert c["lost"] == 0, c
        assert smoke_result["comparisons"]["accounting_closed"] is True
        assert smoke_result["comparisons"]["lost_total"] == 0

    def test_zero_shed_when_healthy_under_capacity(self, smoke_result):
        for c in smoke_result["cells"]:
            assert c["scenario"] == "healthy"
            assert c["shed"] == 0, c
            assert c["shed_rate"] == 0, c
            assert c["errors"] == 0, c

    def test_exactly_once_no_duplicates(self, smoke_result):
        for c in smoke_result["cells"]:
            assert c["duplicates"] == 0, c
        assert smoke_result["comparisons"]["duplicates_total"] == 0

    def test_latencies_recorded(self, smoke_result):
        # TTFT / per-token / queue-wait percentiles exist for every
        # healthy cell — the columns top/metrics/why surface.
        for c in smoke_result["cells"]:
            assert c["ttft_ms_p50"] is not None and c["ttft_ms_p50"] > 0, c
            assert c["tpot_ms_p50"] is not None, c
            assert c["queue_wait_ms_p50"] is not None, c

    def test_zero_router_overhead_without_serving_jobs(self, smoke_result):
        # The idle cell: a non-serving fleet never wakes the router.
        idle = smoke_result["idle_overhead"]
        assert idle["router_io_total"] == 0, idle
        assert all(v == 0 for v in idle["router_io"].values()), idle
        assert idle["serve_dir_exists"] is False, idle
        assert smoke_result["comparisons"]["idle_router_io_zero"] is True

    def test_serving_cells_did_route(self, smoke_result):
        # The mirror of the idle pin: serving cells DID go through the
        # router (ticks, dispatches, publishes all non-zero).
        for c in smoke_result["cells"]:
            io = c["router_io"]
            assert io["ticks"] > 0, c
            assert io["dispatches"] >= c["ok"], c
            assert io["publishes"] >= c["ok"], c

    def test_artifact_shape_is_committed_schema(self, tmp_path):
        out = tmp_path / "bench.json"
        serveplane_bench.run(
            replica_cells=(1,),
            scenarios=("healthy",),
            rate=10.0,
            duration=1.0,
            slots=4,
            tpot_ms=10.0,
            max_new_tokens=4,
            max_queue_depth=64,
            deadline_s=5.0,
            idle_timeout=2.0,
            idle_jobs=2,
            idle_passes=3,
            out=str(out),
            work_dir=str(tmp_path),
            log=lambda *_: None,
        )
        data = json.loads(out.read_text())
        assert data["bench"] == "serve_plane"
        assert {c["cell"] for c in data["cells"]} == {"healthyx1"}
        for field in (
            "offered", "ok", "shed", "errors", "duplicates", "rerouted",
            "accounted", "goodput_rps", "shed_rate", "ttft_ms_p50",
            "ttft_ms_p99", "tpot_ms_p99", "queue_wait_ms_p99", "lost",
            "router_io", "ttft_p99_bound_ms",
        ):
            assert field in data["cells"][0], field
        assert "idle_overhead" in data
        for field in (
            "duplicates_total", "lost_total", "accounting_closed",
            "idle_router_io_zero",
        ):
            assert field in data["comparisons"], field


# ---- chaos through the router path ----


class TestServePlaneChaos:
    def test_kill_replica_rerouted_exactly_once(self, tmp_path):
        """A replica SIGKILLed mid-request: its in-flight requests are
        pulled back and re-routed, the client still sees exactly one
        response per submit, and nothing is lost or duplicated."""
        # 16 tokens x 25ms -> ~0.4s per request at rate 15/s keeps ~6
        # requests in flight on the lone replica, so the kill always
        # catches requests mid-decode.
        cell = serveplane_bench.bench_cell(
            1,
            "kill_replica",
            rate=15.0,
            duration=2.5,
            slots=8,
            tpot_ms=25.0,
            max_new_tokens=16,
            max_queue_depth=64,
            deadline_s=10.0,
            retry_limit=3,
            idle_timeout=2.5,
            state_dir=tmp_path / "state",
            log=lambda *_: None,
        )
        assert cell["rerouted"] >= 1, cell
        assert cell["accounted"] == cell["offered"], cell
        assert cell["lost"] == 0, cell
        assert cell["duplicates"] == 0, cell
        assert cell["errors"] == 0, cell  # retries absorbed the death
        assert cell["ok"] + cell["shed"] == cell["offered"], cell

    def test_fail_engine_step_error_responses_exactly_once(self, tmp_path):
        """An injected engine-step fault aborts one decode block: every
        in-flight casualty gets an error response (nobody blocks on a
        reply nothing will write), later requests complete normally,
        and the closure pins still hold."""
        cell = serveplane_bench.bench_cell(
            1,
            "fail_engine_step",
            rate=15.0,
            duration=2.0,
            slots=8,
            tpot_ms=25.0,
            max_new_tokens=16,
            max_queue_depth=64,
            deadline_s=10.0,
            retry_limit=2,
            idle_timeout=2.5,
            state_dir=tmp_path / "state",
            log=lambda *_: None,
        )
        assert cell["errors"] >= 1, cell
        assert cell["ok"] >= 1, cell  # the engine kept serving after
        assert cell["accounted"] == cell["offered"], cell
        assert cell["lost"] == 0, cell
        assert cell["duplicates"] == 0, cell


# ---- router unit surface (no subprocesses) ----


class _Handle:
    def __init__(self, rtype=ReplicaType.MASTER, index=0, active=True):
        self.replica_type = rtype
        self.index = index
        self._active = active

    def is_active(self):
        return self._active


def _serve_job(**slo):
    return serveplane_bench._make_serve_job(
        "svc", 1, slots=4, tpot_ms=10.0, idle_timeout=0.0,
        max_queue_depth=slo.get("max_queue_depth", 0),
        deadline_s=slo.get("deadline_s", 0.0),
        retry_limit=slo.get("retry_limit", 2),
    )


class TestRouterContracts:
    def test_shed_carries_overload_marker(self, tmp_path):
        """spec.serving.slo depth bar: requests past it get the
        explicit overload response — marker, decision, queue wait."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _serve_job(max_queue_depth=1)
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rids = [front.submit(prompt_len=2, max_new_tokens=4) for _ in range(3)]
        summary = router.tick(key, job, [_Handle()], {})
        assert summary["shed"] == 2, summary
        assert summary["inflight"] == 1, summary
        shed = [r for r in rids if front.has_response(r)]
        assert len(shed) == 2
        for rid in shed:
            resp = front.read_response(rid)
            assert resp["overload"] is True, resp
            assert resp["shed"] == "shed_depth", resp
            assert resp["error"].startswith("shed:"), resp
            assert resp["queue_wait_ms"] >= 0, resp
        # The admitted one is sitting in the replica's private spool.
        rsp = Spool(replica_spool_dir(serve_root_dir(state), key, "Master", 0))
        assert rsp.pending_count() == 1

    def test_router_restart_dedup_publishes_once(self, tmp_path):
        """Router restart mid-flight: the new life re-adopts the front
        claim, finds the copy the engine already answered, and
        publishes exactly once — respond_once makes a second
        publication structurally impossible."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _serve_job()
        r1 = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.submit(prompt_len=2, max_new_tokens=4)
        r1.tick(key, job, [_Handle()], {})

        # The engine's half: claim + respond in the replica spool.
        rsp = Spool(replica_spool_dir(serve_root_dir(state), key, "Master", 0))
        (rec,) = rsp.claim(4)
        assert rec["id"] == rid
        rsp.respond(rid, {"id": rid, "tokens": [0, 1], "ttft_ms": 1.0})

        # A fresh router (the restart): re-adopts, publishes once.
        r2 = ServeRouter(state)
        r2.tick(key, job, [_Handle()], {})
        resp = front.read_response(rid)
        assert resp is not None and resp["tokens"] == [0, 1]
        assert resp["attempts"] >= 1
        files = list(front.responses.glob("*.json"))
        assert [p.stem for p in files] == [rid]
        # Exactly-once is enforced at the publication primitive.
        assert front.respond_once(rid, {"id": rid, "error": "dup"}) is False
        assert front.read_response(rid)["tokens"] == [0, 1]

    def test_spool_stale_tmp_gc(self, tmp_path):
        """Spool hygiene: a .tmp outliving the sweep age belongs to a
        dead writer and is GC'd; fresh tmps and real requests are not."""
        sp = Spool(tmp_path / "spool")
        old = sp.requests / "dead.json.tmp"
        old.write_text("{}")
        os.utime(old, (time.time() - 120, time.time() - 120))
        fresh = sp.requests / "alive.json.tmp"
        fresh.write_text("{}")
        rid = sp.submit(prompt_len=2)
        assert sp.sweep_stale(60.0) == 1
        assert not old.exists()
        assert fresh.exists()
        assert sp.pending_count() == 1
        (rec,) = sp.claim(1)
        assert rec["id"] == rid

    def test_torn_request_gets_error_response(self, tmp_path):
        """Torn-request tolerance: a half-written request file is
        answered with an error instead of wedging the claim scan."""
        sp = Spool(tmp_path / "spool")
        (sp.requests / "torn-1.json").write_text('{"id": "torn-1", "pro')
        good = sp.submit(prompt_len=2)
        recs = sp.claim(4)
        assert [r["id"] for r in recs] == [good]
        resp = sp.read_response("torn-1")
        assert resp is not None and "torn" in resp["error"]


# ---- `tpujob why` cites replica death for the serve plane ----


def _write_status(state, key, replica, recs):
    d = state / "status" / key_to_fs(key)
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"{replica}.jsonl", "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _write_events(state, key, evs):
    d = state / "events"
    d.mkdir(parents=True, exist_ok=True)
    with open(d / (key_to_fs(key) + ".events.jsonl"), "a") as f:
        for ts, etype, reason, msg in evs:
            f.write(
                json.dumps(
                    {
                        "timestamp": ts,
                        "type": etype,
                        "reason": reason,
                        "message": msg,
                        "count": 1,
                    }
                )
                + "\n"
            )


class TestWhyCitesReplicaDeath:
    def test_serve_findings_cite_death_as_cause(self, tmp_path):
        """The postmortem story the serve plane owes: a replica dies,
        the survivors' batch collapses, the front queue ratchets up,
        TTFT spikes — and `tpujob why` says WHY, citing the death
        event as evidence on both serve findings."""
        from pytorch_operator_tpu.obs import analyze as obs_analyze

        state = tmp_path / "state"
        key = "default/svc"
        t0 = time.time() - 60.0

        # Two engines at full batch for 10 beats; worker-0 dies at
        # t0+10; master-0 alone afterwards, its TTFT tail spiking.
        def engine(replica, beats, t_from, slots_free=0, ttft=80.0):
            return [
                {
                    "event": "serve", "ts": t_from + i, "requests": 10 * i,
                    "slots": 4, "slots_free": slots_free, "queued": 4,
                    "pending": 0, "ttft_ms_p50": ttft / 2,
                    "ttft_ms_p99": ttft,
                }
                for i in range(beats)
            ]

        _write_status(state, key, "master-0", engine("master-0", 10, t0))
        _write_status(state, key, "worker-0", engine("worker-0", 10, t0))
        _write_status(
            state, key, "master-0",
            engine("master-0", 4, t0 + 10.5, slots_free=2, ttft=900.0),
        )
        # The router's beat: front queue only grows once capacity halved.
        _write_status(
            state, key, "router",
            [
                {
                    "event": "serve", "ts": t0 + 10.0 + i,
                    "queue_depth": d, "inflight": d + 4, "replicas": 1,
                    "slots_free": 0.0, "routed": 100 + 5 * i, "shed": i,
                }
                for i, d in enumerate([1, 3, 6, 10, 15])
            ],
        )
        _write_events(
            state, key,
            [
                (
                    t0 + 10.0, "Warning", "FaultInjected",
                    "injected kill of default/svc/worker-0 (kill_replica).",
                ),
                (
                    t0 + 10.2, "Warning", "TPUJobRestarting",
                    "replica worker-0 failed (exit 137, retryable); "
                    "restarting.",
                ),
            ],
        )

        report = obs_analyze.analyze(state, key)
        rules = {f["rule"]: f for f in report["findings"]}
        assert "queue_growth" in rules, report["findings"]
        assert "batch_size_collapse" in rules, report["findings"]
        for rule in ("queue_growth", "batch_size_collapse"):
            f = rules[rule]
            # The death is cited IN the finding: summary names the
            # event reason, and the event rides along as evidence.
            assert "FaultInjected" in f["summary"], f
            cited = [e for e in f["evidence"] if e.get("source") == "event"]
            assert cited and cited[0]["reason"] == "FaultInjected", f
