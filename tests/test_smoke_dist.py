"""The distributed canary through the full stack: supervisor gang-launches
N processes, they rendezvous via jax.distributed (gloo CPU collectives) and
run real cross-process collectives. Reference analog: examples/smoke-dist
as the e2e wiring proof (SURVEY.md §4).

Marked slow: each process pays jax import + gloo setup on one CPU core.
"""

import pytest

from pytorch_operator_tpu.api import ProcessTemplate, ReplicaType, Resources
from pytorch_operator_tpu.controller import Supervisor
from tests.testutil import new_job


@pytest.mark.slow
def test_smoke_dist_two_process(tmp_path):
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.1)
    job = new_job(name="smoke-dist", workers=1)
    job.spec.port = None  # auto-allocate: avoid TIME_WAIT across test runs
    for rs in job.spec.replica_specs.values():
        rs.template = ProcessTemplate(
            module="pytorch_operator_tpu.workloads.smoke_dist",
            resources=Resources(cpu_devices=1),
        )
    done = sup.run(job, timeout=240)
    master_log = (tmp_path / "state" / "logs" / "default_smoke-dist-master-0.log").read_text()
    worker_log = (tmp_path / "state" / "logs" / "default_smoke-dist-worker-0.log").read_text()
    assert done.is_succeeded(), f"master log:\n{master_log}\nworker log:\n{worker_log}"
    assert "rank 0: OK" in master_log
    assert "rank 1: OK" in worker_log
    assert "2 processes, 2 global devices" in master_log
    sup.shutdown()
