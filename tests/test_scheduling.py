"""SchedulingPolicy semantics: priority classes, queue capacity, and
minMember gang admission.

Reference: volcano gang scheduling as wired by the common job framework —
PodGroup ``minMember``, queue, and priorityClass (SURVEY.md §2 "Gang
scheduling", §3.5). Tests run against FakeRunner capacity, the
fake-clientset trick (SURVEY.md §4).
"""

from __future__ import annotations

from pytorch_operator_tpu.api.types import ReplicaPhase, ReplicaType, SchedulingPolicy
from pytorch_operator_tpu.controller.runner import FakeRunner, replica_name
from pytorch_operator_tpu.controller.supervisor import Supervisor
from tests.testutil import new_job


def make_sup(capacity):
    return Supervisor(
        state_dir=None, runner=FakeRunner(capacity=capacity), persist=False
    )


def finish_master(sup, key):
    sup.runner.set_phase(
        replica_name(key, ReplicaType.MASTER, 0), ReplicaPhase.SUCCEEDED, exit_code=0
    )


class TestPriority:
    def test_higher_priority_claims_capacity_first(self, tmp_path):
        sup = make_sup(capacity=2)
        lo = new_job(name="lo", workers=1)
        hi = new_job(name="hi", workers=1)
        hi.spec.run_policy.scheduling_policy.priority = 10
        lo_key = sup.submit(lo)  # submitted FIRST, but outranked
        hi_key = sup.submit(hi)
        sup.sync_once()
        assert len(sup.runner.list_for_job(hi_key)) == 2
        assert len(sup.runner.list_for_job(lo_key)) == 0
        assert any(
            e.reason == "Unschedulable" for e in sup.events.for_job(lo_key)
        )

    def test_lower_priority_runs_after_capacity_frees(self, tmp_path):
        sup = make_sup(capacity=2)
        lo = new_job(name="lo", workers=1)
        hi = new_job(name="hi", workers=1)
        hi.spec.run_policy.scheduling_policy.priority = 10
        lo_key = sup.submit(lo)
        hi_key = sup.submit(hi)
        sup.sync_once()
        sup.runner.set_all_running(hi_key)
        finish_master(sup, hi_key)
        sup.sync_once()  # hi completes; CleanPodPolicy frees its slots
        sup.sync_once()
        assert sup.get(hi_key).is_succeeded()
        assert len(sup.runner.list_for_job(lo_key)) == 2

    def test_equal_priority_is_fifo(self, tmp_path):
        sup = make_sup(capacity=2)
        first = sup.submit(new_job(name="first", workers=1))
        second = sup.submit(new_job(name="second", workers=1))
        sup.sync_once()
        assert len(sup.runner.list_for_job(first)) == 2
        assert len(sup.runner.list_for_job(second)) == 0


class TestMinAvailable:
    def test_partial_world_admitted_at_min_available(self, tmp_path):
        """min_available below the total admits a partial gang (volcano
        minMember): the world waits at rendezvous for stragglers, which
        spawn as capacity frees."""
        sup = make_sup(capacity=2)
        job = new_job(name="partial", workers=2)  # total 3
        job.spec.run_policy.scheduling_policy.min_available = 2
        key = sup.submit(job)
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 2  # admitted at minMember
        assert not any(
            e.reason == "Unschedulable" for e in sup.events.for_job(key)
        )
        sup.runner.capacity = 3  # capacity frees → straggler spawns
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 3

    def test_all_or_nothing_by_default(self, tmp_path):
        sup = make_sup(capacity=2)
        key = sup.submit(new_job(name="whole", workers=2))  # total 3
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 0
        assert any(e.reason == "Unschedulable" for e in sup.events.for_job(key))

    def test_master_admitted_first_regardless_of_spec_order(self, tmp_path):
        """replica_specs preserves user YAML key order; a spec listing
        Worker before Master must still put the Master in the admitted
        prefix — a worker-only partial world blocks at rendezvous forever."""
        sup = make_sup(capacity=2)
        job = new_job(name="wfirst", workers=2)  # total 3
        specs = job.spec.replica_specs
        job.spec.replica_specs = {
            ReplicaType.WORKER: specs[ReplicaType.WORKER],
            ReplicaType.MASTER: specs[ReplicaType.MASTER],
        }
        job.spec.run_policy.scheduling_policy.min_available = 2
        key = sup.submit(job)
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 2
        assert (
            sup.runner.get(replica_name(key, ReplicaType.MASTER, 0)) is not None
        )

    def test_gang_disabled_per_job_admits_piecewise(self, tmp_path):
        sup = make_sup(capacity=1)
        job = new_job(name="piecewise", workers=2)  # total 3 > capacity
        job.spec.run_policy.scheduling_policy.gang = False
        key = sup.submit(job)
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 1


class TestQueues:
    def make_queued_sup(self, caps, capacity=None):
        return Supervisor(
            state_dir=None,
            runner=FakeRunner(capacity=capacity),
            persist=False,
            queue_slots=caps,
        )

    def test_queue_capacity_bounds_admission(self, tmp_path):
        sup = self.make_queued_sup({"small": 2})
        a = new_job(name="a", workers=0)
        b = new_job(name="b", workers=0)
        c = new_job(name="c", workers=0)
        for j in (a, b, c):
            j.spec.run_policy.scheduling_policy.queue = "small"
        ka, kb, kc = sup.submit(a), sup.submit(b), sup.submit(c)
        sup.sync_once()
        assert len(sup.runner.list_for_job(ka)) == 1
        assert len(sup.runner.list_for_job(kb)) == 1
        assert len(sup.runner.list_for_job(kc)) == 0
        ev = [e for e in sup.events.for_job(kc) if e.reason == "Unschedulable"]
        assert ev and "queue 'small'" in ev[0].message

    def test_unlisted_queue_is_unbounded(self, tmp_path):
        sup = self.make_queued_sup({"small": 1})
        job = new_job(name="big", workers=3)
        job.spec.run_policy.scheduling_policy.queue = "other"
        key = sup.submit(job)
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 4

    def test_queue_frees_when_job_finishes(self, tmp_path):
        sup = self.make_queued_sup({"q": 1})
        a = new_job(name="a", workers=0)
        b = new_job(name="b", workers=0)
        for j in (a, b):
            j.spec.run_policy.scheduling_policy.queue = "q"
        ka, kb = sup.submit(a), sup.submit(b)
        sup.sync_once()
        sup.runner.set_all_running(ka)
        finish_master(sup, ka)
        sup.sync_once()
        sup.sync_once()
        assert sup.get(ka).is_succeeded()
        assert len(sup.runner.list_for_job(kb)) == 1


class TestReservation:
    def test_held_gang_reserves_slots_against_lower_priority(self, tmp_path):
        """A pending high-priority gang must not be starved by a stream of
        small low-priority jobs: its demand is reserved, so later jobs in
        the pass see no free capacity."""
        sup = make_sup(capacity=3)
        occupier = sup.submit(new_job(name="occupier", workers=0))  # 1 slot
        sup.sync_once()
        hi = new_job(name="hi", workers=2)  # gang of 3 > 2 free
        hi.spec.run_policy.scheduling_policy.priority = 10
        hi_key = sup.submit(hi)
        small = sup.submit(new_job(name="small", workers=0))  # 1 slot, prio 0
        sup.sync_once()
        assert len(sup.runner.list_for_job(hi_key)) == 0  # held
        # The free slots are reserved for hi — small must NOT sneak in.
        assert len(sup.runner.list_for_job(small)) == 0
        # Occupier finishes → 3 free → hi launches; small still waits.
        sup.runner.set_all_running(occupier)
        finish_master(sup, occupier)
        sup.sync_once()
        sup.sync_once()
        assert len(sup.runner.list_for_job(hi_key)) == 3
        assert len(sup.runner.list_for_job(small)) == 0
        # hi finishes → small finally runs.
        sup.runner.set_all_running(hi_key)
        finish_master(sup, hi_key)
        sup.sync_once()
        sup.sync_once()
        assert len(sup.runner.list_for_job(small)) == 1

    def test_scale_down_does_not_wedge_on_stale_min_available(self, tmp_path):
        """set_defaults pins min_available to the submit-time total; an
        elastic scale-down must not leave an unreachable gang threshold."""
        from pytorch_operator_tpu.api.types import ElasticPolicy

        sup = make_sup(capacity=3)
        job = new_job(
            name="elastic", workers=4,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=4, max_restarts=4),
        )
        # Explicit all-or-nothing threshold (overrides the elastic floor).
        job.spec.run_policy.scheduling_policy.min_available = 5
        key = sup.submit(job)  # needs 5 at once > capacity 3 → held
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 0
        sup.scale(key, 1)  # now total 2; the stale threshold 5 must cap to 2
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 2

    def test_unschedulable_blames_binding_constraint(self, tmp_path):
        """With an ample queue but tight runner slots, the event must blame
        capacity — not point the operator at the queue."""
        sup = Supervisor(
            state_dir=None,
            runner=FakeRunner(capacity=2),
            persist=False,
            queue_slots={"big": 100},
        )
        job = new_job(name="tight", workers=2)  # gang of 3 > 2 slots
        job.spec.run_policy.scheduling_policy.queue = "big"
        key = sup.submit(job)
        sup.sync_once()
        ev = [e for e in sup.events.for_job(key) if e.reason == "Unschedulable"]
        assert ev and "available capacity" in ev[0].message
        assert "queue" not in ev[0].message


class TestSoloSyncIsolation:
    def test_foreground_wait_ignores_stale_pass_reservations(self, tmp_path):
        """A held gang's reservation from a daemon-style sync_once pass must
        not starve a later foreground run(): solo syncs ignore pass state."""
        sup = make_sup(capacity=2)
        big = new_job(name="big", workers=3)  # gang of 4 > 2 → held, reserves
        sup.submit(big)
        sup.sync_once()
        small_key = sup.submit(new_job(name="small", workers=0))
        # Foreground wait() path = solo reconciler.sync calls, no pass.
        sup.reconciler.sync(small_key)
        assert len(sup.runner.list_for_job(small_key)) == 1  # admitted
        # A daemon pass still honors the reservation: nothing for big, and
        # a THIRD job submitted at prio 0 is blocked by big's claim.
        third_key = sup.submit(new_job(name="third", workers=0))
        sup.sync_once()
        assert len(sup.runner.list_for_job(third_key)) == 0


class TestCLIQueueSlots:
    def test_parse_and_reject(self):
        import pytest

        from pytorch_operator_tpu.client.cli import _parse_queue_slots

        assert _parse_queue_slots("a=4, b=2".replace(" ", "")) == {"a": 4, "b": 2}
        assert _parse_queue_slots(None) is None
        for bad in ("a=0", "a=-2", "a=4,a=1", "a", "=4", "a=x"):
            with pytest.raises(SystemExit):
                _parse_queue_slots(bad)


class TestAPI:
    def test_priority_round_trips(self):
        sp = SchedulingPolicy(priority=7, queue="batch", min_available=3)
        got = SchedulingPolicy.from_dict(sp.to_dict())
        assert got == sp

    def test_priority_defaults_to_zero(self):
        assert SchedulingPolicy.from_dict({}).priority == 0
        assert SchedulingPolicy.from_dict({"priority": None}).priority == 0

    def test_numeric_queue_name_coerced_to_string(self):
        sp = SchedulingPolicy.from_dict({"queue": 5})
        assert sp.queue == "5"

    def test_priority_bad_value_names_field(self):
        import pytest

        with pytest.raises(ValueError, match="scheduling_policy.priority"):
            SchedulingPolicy.from_dict({"priority": "high"})
