"""Condition state-machine matrix — the behavioral subtlety SURVEY.md §7
flags ("getting the condition state machine exactly faithful ... is where
the reference's behavioral subtlety lives"). Explicit exclusivity matrix
and timestamp semantics, mirroring the reference's updateJobConditions.
"""

from __future__ import annotations

import pytest

from pytorch_operator_tpu.api.types import ConditionType, TPUJob

C = ConditionType
CURRENT_STATE = (C.RUNNING, C.RESTARTING, C.SUSPENDED)
TERMINAL = (C.SUCCEEDED, C.FAILED)


class TestExclusivityMatrix:
    @pytest.mark.parametrize("new", CURRENT_STATE)
    @pytest.mark.parametrize("old", CURRENT_STATE)
    def test_current_state_conditions_are_mutually_exclusive(self, old, new):
        if old == new:
            pytest.skip("same condition")
        job = TPUJob()
        job.set_condition(old)
        job.set_condition(new)
        assert job.has_condition(new)
        assert not job.has_condition(old)

    @pytest.mark.parametrize("terminal", TERMINAL)
    @pytest.mark.parametrize("state", CURRENT_STATE)
    def test_terminal_clears_every_current_state(self, state, terminal):
        job = TPUJob()
        job.set_condition(state)
        job.set_condition(terminal)
        assert job.has_condition(terminal)
        assert not job.has_condition(state)
        assert job.is_finished()

    def test_created_survives_everything(self):
        job = TPUJob()
        job.set_condition(C.CREATED)
        for ct in CURRENT_STATE + TERMINAL:
            job.set_condition(ct)
        assert job.has_condition(C.CREATED)

    def test_cleared_condition_keeps_history_entry(self):
        """Clearing flips status to False but keeps the entry (the
        reference keeps the full condition list with status flags)."""
        job = TPUJob()
        job.set_condition(C.RUNNING)
        job.set_condition(C.RESTARTING)
        running = job.get_condition(C.RUNNING)
        assert running is not None and running.status is False


class TestTimestamps:
    def test_transition_time_only_moves_on_status_flip(self):
        job = TPUJob()
        job.set_condition(C.RUNNING, reason="a", now=100.0)
        c = job.get_condition(C.RUNNING)
        assert c.last_transition_time == 100.0
        # Same status, later update: update time moves, transition stays.
        job.set_condition(C.RUNNING, reason="b", now=200.0)
        c = job.get_condition(C.RUNNING)
        assert c.last_update_time == 200.0
        assert c.last_transition_time == 100.0
        # Flip off (via RESTARTING) and back on: transition moves.
        job.set_condition(C.RESTARTING, now=300.0)
        job.set_condition(C.RUNNING, now=400.0)
        c = job.get_condition(C.RUNNING)
        assert c.last_transition_time == 400.0

    def test_exclusive_clear_stamps_both_times(self):
        job = TPUJob()
        job.set_condition(C.RUNNING, now=100.0)
        job.set_condition(C.SUSPENDED, now=250.0)
        running = job.get_condition(C.RUNNING)
        assert running.status is False
        assert running.last_transition_time == 250.0
        assert running.last_update_time == 250.0

    def test_reason_and_message_persist_unless_replaced(self):
        job = TPUJob()
        job.set_condition(C.RUNNING, reason="r1", message="m1", now=1.0)
        job.set_condition(C.RUNNING, now=2.0)  # empty reason/message
        c = job.get_condition(C.RUNNING)
        assert c.reason == "r1" and c.message == "m1"
