"""Live health engine (obs/watch.py + obs/rules.py) tests.

- lifecycle units: pending→firing→resolved hysteresis (``for_s`` /
  ``clear_s``), dedup by (job, rule, replica), one log append per
  transition, re-detection = a new instance, finalize-on-finish;
- every rule fires LIVE from a synthetic rolling window, and a healthy
  window alerts nothing;
- the cross-job noisy-neighbor correlation;
- spec overrides: ``spec.observability.alerts`` thresholds suppress a
  live alert AND an offline ``tpujob why`` finding (one bar, two
  engines), validation rejects typo'd threshold names, the policy
  threads into replica env;
- offline-vs-live parity: the same recorded timeline produces the same
  rule set from ``analyze()`` and from a watch replay;
- subprocess e2e: drop_heartbeat fires a heartbeat_silence alert
  BEFORE the TPUJobHung kill and the alert is cited (resolved) in the
  subsequent ``tpujob why``; a bounded drop resolves after recovery; a
  persistent-ENOSPC world fires checkpoint_lag; a feed-stalled world
  fires feed_stall_dominance;
- bench_smoke: a healthy world's watch evaluates rules with zero
  alerts and ZERO log appends (the idle-I/O pin rides
  test_ctrlplane_bench for the store side).
"""

from __future__ import annotations

import json
import time

import pytest

from pytorch_operator_tpu import faults
from pytorch_operator_tpu.api import (
    AlertPolicy,
    ObjectMeta,
    ObservabilityPolicy,
    ProcessTemplate,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    TPUJob,
    TPUJobSpec,
    set_defaults,
)
from pytorch_operator_tpu.api.defaults import HANG_DEADLINE_ANNOTATION
from pytorch_operator_tpu.controller.metrics import Gauge
from pytorch_operator_tpu.controller.store import key_to_fs
from pytorch_operator_tpu.controller.supervisor import Supervisor
from pytorch_operator_tpu.faults import Fault, FaultPlan
from pytorch_operator_tpu.obs import analyze as obs_analyze
from pytorch_operator_tpu.obs import rules as obs_rules
from pytorch_operator_tpu.obs import watch as obs_watch

KEY = "default/w"


def _beat(ts, step, step_time_ms=10.0, **extra):
    return {
        "ts": float(ts),
        "step": float(step),
        "steps_per_sec": 1000.0 / step_time_ms,
        "step_time_ms": float(step_time_ms),
        **extra,
    }


def _feed(eng, key, replica, beats, kind="progress"):
    for b in beats:
        eng.ingest_record(key, replica, kind, b)


def _steady(eng, key, replica="master-0", n=12, t0=100.0, dt=0.1,
            step_time_ms=10.0, **extra):
    _feed(
        eng, key, replica,
        [_beat(t0 + i * dt, i + 1, step_time_ms, **extra) for i in range(n)],
    )
    return t0 + (n - 1) * dt


def _policy_job(name="test-job", alerts=None, workers=0):
    from tests.testutil import new_job

    job = new_job(name=name, workers=workers)
    if alerts is not None:
        job.spec.observability = ObservabilityPolicy(alerts=alerts)
    return job


def _rules_of(alerts):
    return sorted({a.rule for a in alerts})


# ---- lifecycle ----


class TestLifecycle:
    def test_silence_fires_immediately_by_default(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)  # beats every 0.1s -> threshold 1.0s
        assert eng.evaluate(KEY, now=t_end + 0.3) == []
        alerts = eng.evaluate(KEY, now=t_end + 1.5)
        assert [a.state for a in alerts] == ["firing"]
        a = alerts[0]
        assert a.rule == "heartbeat_silence"
        assert a.replica == "master-0"
        assert a.severity == "critical"
        assert a.evidence  # cites the last beat
        # The transition (and only the transition) hit the log.
        assert eng.io.log_appends == 1
        recs = obs_watch.load_alert_log(tmp_path, KEY)
        assert len(recs) == 1 and recs[0]["state"] == "firing"

    def test_steady_firing_appends_nothing(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)
        eng.evaluate(KEY, now=t_end + 1.5)
        for i in range(10):
            eng.evaluate(KEY, now=t_end + 1.6 + 0.1 * i)
        assert eng.io.log_appends == 1  # dedup: one instance, one record

    def test_resolve_after_clear_duration(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)
        eng.evaluate(KEY, now=t_end + 1.5)
        # Recovery: beats resume (and KEEP coming — a one-off beat
        # followed by nothing would be a fresh silence)...
        _feed(eng, KEY, "master-0",
              [_beat(t_end + 1.6 + 0.1 * i, 20 + i) for i in range(60)])
        still = eng.evaluate(KEY, now=t_end + 1.75)
        # ...but clear_s (default 5s) hysteresis keeps it firing first.
        assert [a.state for a in still] == ["firing"]
        assert eng.evaluate(KEY, now=t_end + 3.0) != []
        assert eng.evaluate(KEY, now=t_end + 7.5) == []
        recs = obs_watch.load_alert_log(tmp_path, KEY)
        assert [r["state"] for r in recs] == ["firing", "resolved"]

    def test_for_s_hysteresis_and_blip_drop(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        job = _policy_job(alerts=AlertPolicy(for_s=1.0))
        key = "default/test-job"
        t_end = _steady(eng, key)
        # First detection: pending, not firing (must persist for_s).
        alerts = eng.evaluate(key, job=job, now=t_end + 1.5)
        assert [a.state for a in alerts] == ["pending"]
        assert eng.io.log_appends == 0
        alerts = eng.evaluate(key, job=job, now=t_end + 1.9)
        assert [a.state for a in alerts] == ["pending"]
        # A blip: the condition clears one pass -> pending is dropped.
        _feed(eng, key, "master-0", [_beat(t_end + 2.0, 99)])
        assert eng.evaluate(key, job=job, now=t_end + 2.1) == []
        assert eng.io.log_appends == 0
        # Persistent silence: pending ages past for_s -> firing.
        eng.evaluate(key, job=job, now=t_end + 3.5)
        alerts = eng.evaluate(key, job=job, now=t_end + 4.6)
        assert [a.state for a in alerts] == ["firing"]
        assert eng.io.log_appends == 1

    def test_dedup_is_per_replica(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        _steady(eng, KEY, replica="worker-0")
        t_end = _steady(eng, KEY, replica="worker-1")
        alerts = eng.evaluate(KEY, now=t_end + 2.0)
        assert len(alerts) == 2
        assert {a.replica for a in alerts} == {"worker-0", "worker-1"}
        assert eng.io.log_appends == 2

    def test_redetection_is_a_new_instance(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        job = _policy_job(alerts=AlertPolicy(clear_s=0.5))
        key = "default/test-job"
        t_end = _steady(eng, key)
        eng.evaluate(key, job=job, now=t_end + 1.5)  # firing #1
        _feed(eng, key, "master-0", [_beat(t_end + 1.6, 99)])
        eng.evaluate(key, job=job, now=t_end + 1.7)
        eng.evaluate(key, job=job, now=t_end + 2.5)  # resolved #1
        alerts = eng.evaluate(key, job=job, now=t_end + 4.0)  # firing #2
        assert [a.state for a in alerts] == ["firing"]
        recs = obs_watch.load_alert_log(tmp_path, key)
        assert [r["state"] for r in recs] == ["firing", "resolved", "firing"]

    def test_finalize_resolves_firing(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)
        eng.evaluate(KEY, now=t_end + 1.5)
        eng.finalize(KEY, now=t_end + 2.0)
        eng.finalize(KEY, now=t_end + 2.1)  # idempotent
        recs = obs_watch.load_alert_log(tmp_path, KEY)
        assert [r["state"] for r in recs] == ["firing", "resolved"]
        assert "(job finished)" in recs[-1]["summary"]
        assert eng.active_alerts(KEY) == []

    def test_export_gauge_counts_firing_only(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)
        g = Gauge("tpujob_alerts")
        eng.evaluate(KEY, now=t_end + 0.2)  # healthy: nothing
        eng.export_gauge(g)
        assert g.series_count() == 0
        eng.evaluate(KEY, now=t_end + 1.5)
        eng.export_gauge(g)
        assert g.get(
            job=KEY, rule="heartbeat_silence", severity="critical"
        ) == 1
        eng.finalize(KEY, now=t_end + 2.0)
        eng.export_gauge(g)
        assert g.series_count() == 0

    def test_disabled_policy_resolves_and_stops(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)
        eng.evaluate(KEY, now=t_end + 1.5)
        job = _policy_job(alerts=AlertPolicy(enabled=False))
        assert eng.evaluate(KEY, job=job, now=t_end + 1.6) == []
        recs = obs_watch.load_alert_log(tmp_path, KEY)
        assert [r["state"] for r in recs] == ["firing", "resolved"]

    def test_retire_drops_state_without_logging(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY)
        eng.evaluate(KEY, now=t_end + 1.5)
        before = eng.io.log_appends
        eng.retire_job(KEY)
        assert eng.io.log_appends == before
        assert not eng.tracked(KEY)


# ---- every rule, live ----


class TestLiveRules:
    def test_healthy_window_is_clean(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY, n=30)
        _feed(
            eng, KEY, "master-0",
            [{"ts": 100.0 + i, "step": float(5 * (i + 1)), "commit_ms": 4.0}
             for i in range(5)],
            kind="checkpoint_committed",
        )
        # Evaluated right at the newest beat: every rule ran, none hit.
        assert eng.evaluate(KEY, now=t_end + 0.1) == []
        assert eng.io.evaluations == 1
        assert eng.io.log_appends == 0

    def test_step_time_regression_fires(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        _steady(eng, KEY, n=24, step_time_ms=10.0)
        _feed(
            eng, KEY, "master-0",
            [_beat(102.4 + 0.1 * i, 30 + i, 40.0) for i in range(8)],
        )
        alerts = eng.evaluate(KEY, now=103.2)
        assert _rules_of(alerts) == ["step_time_regression"]
        assert alerts[0].metrics["factor"] > 2.0

    def test_feed_stall_dominance_fires(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY, n=10, feed_stall_ms=8.0)
        alerts = eng.evaluate(KEY, now=t_end)
        assert _rules_of(alerts) == ["feed_stall_dominance"]

    def test_checkpoint_lag_fires(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, KEY, n=20, dt=0.1)  # steps 1..20
        _feed(
            eng, KEY, "master-0",
            [{"ts": 100.0 + i * 0.2, "step": float(2 * (i + 1)),
              "commit_ms": 4.0} for i in range(3)],  # commits 2, 4, 6
            kind="checkpoint_committed",
        )
        alerts = eng.evaluate(KEY, now=t_end)
        assert _rules_of(alerts) == ["checkpoint_lag"]
        assert alerts[0].metrics["lag_steps"] == 14

    def test_straggler_fires(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        _steady(eng, KEY, replica="worker-0", n=8, step_time_ms=10.0)
        _steady(eng, KEY, replica="worker-1", n=8, step_time_ms=10.0)
        t_end = _steady(eng, KEY, replica="worker-2", n=8, step_time_ms=30.0)
        alerts = eng.evaluate(KEY, now=t_end)
        assert _rules_of(alerts) == ["straggler"]
        assert alerts[0].replica == "worker-2"


# ---- noisy neighbor ----


class TestNoisyNeighbor:
    def _regress(self, eng, key):
        _steady(eng, key, n=24, step_time_ms=10.0)
        _feed(
            eng, key, "master-0",
            [_beat(102.4 + 0.1 * i, 30 + i, 40.0) for i in range(8)],
        )
        eng.evaluate(key, now=103.2)

    def test_two_jobs_regressing_attribute_to_host(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path, host="tpu-host-7")
        self._regress(eng, "default/a")
        self._regress(eng, "default/b")
        eng.correlate(now=103.2)
        for key in ("default/a", "default/b"):
            rules = _rules_of(eng.active_alerts(key))
            assert rules == ["noisy_neighbor", "step_time_regression"]
            nn = next(
                a for a in eng.active_alerts(key) if a.rule == "noisy_neighbor"
            )
            assert "tpu-host-7" in nn.summary
            other = "default/b" if key == "default/a" else "default/a"
            assert other in nn.summary
            assert any(ev.get("job") == other for ev in nn.evidence)

    def test_single_regression_stays_unattributed(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        self._regress(eng, "default/a")
        eng.correlate(now=103.2)
        assert _rules_of(eng.active_alerts("default/a")) == [
            "step_time_regression"
        ]

    def test_neighbor_alert_resolves_when_partner_recovers(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        self._regress(eng, "default/a")
        self._regress(eng, "default/b")
        eng.correlate(now=103.2)
        # b recovers (its regression drops out of the pass verdicts).
        _feed(
            eng, "default/b", "master-0",
            [_beat(103.3 + 0.1 * i, 60 + i, 10.0) for i in range(30)],
        )
        eng.evaluate("default/b", now=106.3)
        eng.correlate(now=106.3)
        eng.correlate(now=112.0)  # past clear_s
        assert "noisy_neighbor" not in _rules_of(
            eng.active_alerts("default/a")
        )


# ---- spec overrides: one bar for live and offline ----


def _write_status(state, key, replica, recs) -> None:
    d = state / "status" / key_to_fs(key)
    d.mkdir(parents=True, exist_ok=True)
    with open(d / f"{replica}.jsonl", "a") as f:
        for r in recs:
            f.write(json.dumps(r) + "\n")


def _status_beats(t0, n, interval, step0=1, step_time_ms=10.0, **extra):
    return [
        {
            "event": "progress",
            "ts": t0 + i * interval,
            "step": step0 + i,
            "steps_per_sec": 1000.0 / step_time_ms,
            "step_time_ms": step_time_ms,
            **extra,
        }
        for i in range(n)
    ]


class TestSpecOverrides:
    REGRESSING = (
        _status_beats(100.0, 24, 0.1, step_time_ms=10.0)
        + _status_beats(102.4, 8, 0.1, step0=25, step_time_ms=40.0)
    )

    def test_threshold_override_suppresses_live_alert(self, tmp_path):
        eng = obs_watch.WatchEngine(tmp_path)
        loose = _policy_job(
            alerts=AlertPolicy(thresholds={"regression_factor": 10.0})
        )
        key = "default/test-job"
        for b in self.REGRESSING:
            eng.ingest_record(key, "master-0", "progress", b)
        assert eng.evaluate(key, job=loose, now=103.2) == []
        # The identical window under defaults DOES alert.
        assert _rules_of(eng.evaluate(key, now=103.2)) == [
            "step_time_regression"
        ]

    def test_why_respects_stored_override(self, tmp_path):
        from pytorch_operator_tpu.controller.store import JobStore

        state = tmp_path / "state"
        key = "default/test-job"
        _write_status(state, key, "master-0", self.REGRESSING)
        # Default bar: the offline engine flags the regression.
        report = obs_analyze.analyze(state, key)
        assert "step_time_regression" in [
            f["rule"] for f in report["findings"]
        ]
        # Store the job WITH a loosened bar: same artifacts, no finding.
        job = _policy_job(
            alerts=AlertPolicy(thresholds={"regression_factor": 10.0})
        )
        JobStore(persist_dir=state / "jobs").add(job)
        report = obs_analyze.analyze(state, key)
        assert "step_time_regression" not in [
            f["rule"] for f in report["findings"]
        ]

    def test_validation_rejects_typos_and_negatives(self):
        from pytorch_operator_tpu.api.validation import validate

        job = _policy_job(
            alerts=AlertPolicy(thresholds={"regresion_factor": 2.0})
        )
        with pytest.raises(Exception) as ei:
            validate(job)
        assert "unknown rule threshold" in str(ei.value)
        job = _policy_job(alerts=AlertPolicy(for_s=-1.0))
        with pytest.raises(Exception) as ei:
            validate(job)
        assert "for_s" in str(ei.value)
        # A correctly-spelled override validates.
        validate(_policy_job(
            alerts=AlertPolicy(thresholds={"silence_min_s": 5.0})
        ))

    def test_policy_roundtrips_and_threads_into_env(self):
        from pytorch_operator_tpu.api.serialization import job_from_dict
        from pytorch_operator_tpu.runtime.env import build_cluster_env

        job = _policy_job(
            alerts=AlertPolicy(
                for_s=2.0, clear_s=10.0,
                thresholds={"silence_min_s": 5.0},
            )
        )
        back = job_from_dict(job.to_dict())
        al = back.spec.observability.alerts
        assert al.for_s == 2.0 and al.clear_s == 10.0
        assert al.thresholds == {"silence_min_s": 5.0}
        env = build_cluster_env(back, ReplicaType.MASTER, 0)
        threaded = json.loads(env["TPUJOB_ALERTS"])
        assert threaded["for_s"] == 2.0
        assert threaded["thresholds"]["silence_min_s"] == 5.0
        # No block -> no env key (replicas see only what the spec set).
        assert "TPUJOB_ALERTS" not in build_cluster_env(
            _policy_job(), ReplicaType.MASTER, 0
        )

    def test_thresholds_from_overrides_ignores_unknown(self):
        th = obs_rules.thresholds_from_overrides(
            {"regression_factor": 3.0, "bogus": 1.0,
             "straggler_min_samples": 6.0}
        )
        assert th.regression_factor == 3.0
        assert th.straggler_min_samples == 6  # int field coerced
        assert th.silence_min_s == obs_rules.DEFAULT_THRESHOLDS.silence_min_s


# ---- offline-vs-live parity: same timeline -> same findings ----


class TestParity:
    def _scenarios(self):
        return {
            "step_time_regression": {
                "master-0": (
                    _status_beats(100.0, 24, 0.1, step_time_ms=10.0)
                    + _status_beats(102.4, 8, 0.1, step0=25, step_time_ms=40.0)
                ),
            },
            "feed_stall_dominance": {
                "master-0": _status_beats(
                    100.0, 10, 0.1, step_time_ms=10.0, feed_stall_ms=8.0
                ),
            },
            "straggler": {
                "worker-0": _status_beats(100.0, 8, 0.1, step_time_ms=10.0),
                "worker-1": _status_beats(100.0, 8, 0.1, step_time_ms=10.0),
                "worker-2": _status_beats(100.0, 8, 0.1, step_time_ms=30.0),
            },
            "heartbeat_silence": {
                "worker-0": _status_beats(100.0, 5, 0.5),
                "worker-1": _status_beats(100.0, 21, 0.5),
            },
            "healthy": {
                "master-0": _status_beats(100.0, 30, 0.1, step_time_ms=10.0),
            },
        }

    @pytest.mark.parametrize(
        "scenario",
        ["step_time_regression", "feed_stall_dominance", "straggler",
         "heartbeat_silence", "healthy"],
    )
    def test_same_timeline_same_findings(self, tmp_path, scenario):
        recs_by_replica = self._scenarios()[scenario]
        state = tmp_path / "state"
        key = f"default/{scenario.replace('_', '-')}"
        t_end = 0.0
        for replica, recs in recs_by_replica.items():
            _write_status(state, key, replica, recs)
            t_end = max(t_end, recs[-1]["ts"])

        # Offline: the postmortem engine over the recorded artifacts.
        offline = {
            f["rule"] for f in obs_analyze.analyze(state, key)["findings"]
        }

        # Live: replay the identical records through the watch and
        # evaluate at the recording's end (the live silence reference —
        # the supervisor clock — coincides with the newest beat there).
        eng = obs_watch.WatchEngine(tmp_path / "watch-state")
        for replica, recs in recs_by_replica.items():
            for r in recs:
                eng.ingest_record(key, replica, "progress", r)
        live = {a.rule for a in eng.evaluate(key, now=t_end)}

        assert offline == live
        if scenario == "healthy":
            assert offline == set()
        else:
            assert scenario in offline

    def test_checkpoint_lag_parity(self, tmp_path):
        state = tmp_path / "state"
        key = "default/lag"
        beats = _status_beats(100.0, 20, 0.1)
        commits = [
            {"event": "checkpoint_committed", "ts": 100.05 + i * 0.2,
             "step": 2 * (i + 1), "commit_ms": 4.0}
            for i in range(3)
        ]
        _write_status(state, key, "master-0", beats + commits)
        offline = {
            f["rule"] for f in obs_analyze.analyze(state, key)["findings"]
        }
        eng = obs_watch.WatchEngine(tmp_path / "watch-state")
        for r in beats:
            eng.ingest_record(key, "master-0", "progress", r)
        for r in commits:
            eng.ingest_record(key, "master-0", "checkpoint_committed", r)
        live = {a.rule for a in eng.evaluate(key, now=beats[-1]["ts"])}
        assert offline == live == {"checkpoint_lag"}


# ---- surfaces: log fold, CLI table, top column, diff ----


class TestSurfaces:
    def _seed_log(self, tmp_path, key=KEY):
        eng = obs_watch.WatchEngine(tmp_path)
        t_end = _steady(eng, key)
        eng.evaluate(key, now=t_end + 1.5)
        return eng, t_end

    def test_fold_keeps_latest_state_per_key(self, tmp_path):
        eng, t_end = self._seed_log(tmp_path)
        _feed(eng, KEY, "master-0",
              [_beat(t_end + 1.6 + 0.1 * i, 20 + i) for i in range(70)])
        eng.evaluate(KEY, now=t_end + 1.75)
        eng.evaluate(KEY, now=t_end + 8.0)  # resolved
        folded = obs_watch.fold_alert_log(
            obs_watch.load_alert_log(tmp_path, KEY)
        )
        assert len(folded) == 1
        assert folded[0]["state"] == "resolved"

    def test_alert_table_and_render_text(self, tmp_path):
        eng, _ = self._seed_log(tmp_path)
        rows = obs_watch.gather_alert_rows(tmp_path)
        assert rows and rows[0]["rule"] == "heartbeat_silence"
        table = obs_watch.render_alert_table(rows)
        assert "heartbeat_silence" in table and "firing" in table
        live = eng.render_text()
        assert "1 firing" in live and KEY in live
        assert obs_watch.render_alert_table([]) == "no alerts"

    def test_top_rows_show_firing_alerts(self, tmp_path):
        from pytorch_operator_tpu.controller.store import JobStore
        from pytorch_operator_tpu.obs import top as obs_top

        state = tmp_path / "state"
        job = _policy_job()
        key = "default/test-job"
        JobStore(persist_dir=state / "jobs").add(job)
        _write_status(state, key, "master-0", _status_beats(100.0, 3, 0.1))
        eng = obs_watch.WatchEngine(state)
        t_end = _steady(eng, key)
        eng.evaluate(key, now=t_end + 1.5)
        rows = obs_top.gather_rows(state)
        row = next(r for r in rows if r["job"] == key)
        assert row["alerts"] == 1
        assert row["alert_rules"] == ["heartbeat_silence"]
        plain = obs_top.render_table(rows)
        assert "1:heartbeat_silence" in plain
        assert "\x1b[31m" not in plain
        colored = obs_top.render_table(rows, color=True)
        assert "\x1b[31m" in colored

    def test_diff_rows_semantics(self):
        from pytorch_operator_tpu.obs.top import diff_rows

        base = {
            "job": "default/a", "step": 10, "steps_per_sec": 5.0,
            "p50_ms": 10.0, "p99_ms": 12.0, "ckpt_lag": 1,
            "feed_stall_ms": 0.1, "age_s": 1.0, "alerts": None,
            "alert_rules": [], "restarts": 0, "p99_span": None,
        }
        cur = dict(base)
        cur["steps_per_sec"] = 2.0
        cur["alerts"] = 1
        cur["alert_rules"] = ["heartbeat_silence"]
        cur["age_s"] = 9.0
        lines = diff_rows([base], [cur])
        assert len(lines) == 1
        assert "steps/s 5.00→2.00 ▼" in lines[0]
        assert "ALERT firing: heartbeat_silence" in lines[0]
        assert "going silent" in lines[0]
        # Unchanged -> no output; appear/gone -> named.
        assert diff_rows([base], [dict(base)]) == []
        assert diff_rows([], [base]) == ["default/a: appeared (step 10)"]
        assert diff_rows([base], []) == [
            "default/a: gone (finished or deleted)"
        ]
        recovered = dict(base)
        lines = diff_rows([cur], [recovered])
        assert any("alert resolved: heartbeat_silence" in ln for ln in lines)

    def test_purge_reclaims_alert_log(self, tmp_path):
        from pytorch_operator_tpu.controller.store import purge_job_artifacts

        self._seed_log(tmp_path)
        assert obs_watch.job_alert_log(tmp_path, KEY).exists()
        purge_job_artifacts(tmp_path, KEY)
        assert not obs_watch.job_alert_log(tmp_path, KEY).exists()


# ---- round-trip clock probe ----


class TestRoundTripProbe:
    def test_estimator_prefers_roundtrip_midpoints(self):
        from pytorch_operator_tpu.obs.clock import estimate_offset

        # Replica clock 3s behind; one-way delay a biased 0.4s.
        one_way = [(100.0 + i, 100.0 + i + 3.0 + 0.4) for i in range(10)]
        est = estimate_offset(one_way)
        assert est.rt_n == 0
        assert est.offset_s > 3.2  # the one-way bias, visible
        # Round trips bracket the echo: probe at send+3-0.1 (supervisor
        # clock), observe at send+3+0.1 -> midpoint exactly offset.
        rt = [
            (100.0 + i, 100.0 + i + 3.0 + 0.1, 100.0 + i + 3.0 - 0.1)
            for i in range(5)
        ]
        est = estimate_offset(one_way + rt)
        assert est.rt_n == 5
        assert est.offset_s == pytest.approx(3.0, abs=0.02)
        assert est.to_dict()["rt_n"] == 5

    def test_clock_log_roundtrip_records(self, tmp_path):
        from pytorch_operator_tpu.obs.clock import (
            ClockLog, job_clock_log, load_observations,
        )

        log = ClockLog(job_clock_log(tmp_path, KEY))
        log.observe("master-0", 100.0, 100.5)
        log.observe("master-0", 101.0, 101.5, probe_ts=100.9)
        obs = load_observations(job_clock_log(tmp_path, KEY))["master-0"]
        assert (100.0, 100.5) in obs
        assert (101.0, 101.5, 100.9) in obs

    def test_probe_write_and_replica_echo(self, tmp_path, monkeypatch):
        from pytorch_operator_tpu.obs.clock import read_probe, write_probe
        from pytorch_operator_tpu.runtime import rendezvous

        status = tmp_path / "status"
        status.mkdir()
        assert read_probe(status) is None
        write_probe(status, 123.456)
        probe = read_probe(status)
        assert probe["probe_ts"] == 123.456
        # The replica echoes it once per seq on the heartbeat cadence.
        monkeypatch.setenv("TPUJOB_STATUS_DIR", str(status))
        monkeypatch.setenv("TPUJOB_REPLICA_TYPE", "Master")
        monkeypatch.setenv("TPUJOB_REPLICA_INDEX", "0")
        monkeypatch.setattr(rendezvous, "_probe_echoed_seq", None)
        rendezvous.report_progress(1, steps_per_sec=10.0)
        rendezvous.report_progress(2, steps_per_sec=10.0)
        lines = (status / "master-0.jsonl").read_text().splitlines()
        echoes = [
            json.loads(ln) for ln in lines
            if json.loads(ln)["event"] == "clock_probe"
        ]
        assert len(echoes) == 1  # one echo per probe seq, not per beat
        assert echoes[0]["probe_ts"] == 123.456
        # A NEW probe gets a new echo.
        write_probe(status, 200.0)
        rendezvous.report_progress(3, steps_per_sec=10.0)
        lines = (status / "master-0.jsonl").read_text().splitlines()
        echoes = [
            json.loads(ln) for ln in lines
            if json.loads(ln)["event"] == "clock_probe"
        ]
        assert len(echoes) == 2

    def test_supervisor_folds_echo_into_roundtrip_log(self, tmp_path):
        from pytorch_operator_tpu.controller import FakeRunner
        from pytorch_operator_tpu.obs.clock import (
            job_clock_log, load_observations, read_probe,
        )

        sup = Supervisor(state_dir=tmp_path / "state", runner=FakeRunner())
        try:
            d = tmp_path / "state" / "status" / key_to_fs(KEY)
            d.mkdir(parents=True, exist_ok=True)

            def write(rec):
                with open(d / "master-0.jsonl", "a") as f:
                    f.write(json.dumps(rec) + "\n")

            # A fresh beat makes the supervisor write its first probe.
            write({"event": "progress", "ts": 100.0, "step": 1})
            sup._progress.poll(d)
            sup._record_clock_observations(KEY, d)
            probe = read_probe(d)
            assert probe is not None  # the beat triggered the probe

            # The replica's echo of THAT seq is folded as a round trip
            # (no priming: the seq proves it answers this daemon).
            write({"event": "clock_probe", "ts": 101.0,
                   "probe_ts": probe["probe_ts"], "seq": probe["seq"]})
            sup._progress.poll(d)
            sup._record_clock_observations(KEY, d)
            got = load_observations(job_clock_log(tmp_path / "state", KEY))
            assert len(got["master-0"]) == 1
            send, _observed, echoed = got["master-0"][0]
            assert (send, echoed) == (101.0, probe["probe_ts"])

            # An echo of a seq this daemon never wrote (a pre-restart
            # straggler) is rejected.
            write({"event": "clock_probe", "ts": 102.0,
                   "probe_ts": 50.0, "seq": 999})
            sup._progress.poll(d)
            sup._record_clock_observations(KEY, d)
            got = load_observations(job_clock_log(tmp_path / "state", KEY))
            assert len(got["master-0"]) == 1
        finally:
            sup.shutdown()


# ---- chaos --record ----


class TestChaosRecord:
    def test_no_failure_recorded_is_an_error(self, tmp_path, capsys):
        from pytorch_operator_tpu.client.cli import main

        state = tmp_path / "state"
        _write_status(state, "default/ok", "master-0",
                      _status_beats(100.0, 5, 0.1))
        assert main(
            ["--state-dir", str(state), "chaos", "ok", "--record"]
        ) == 1
        assert "no replayable failure" in capsys.readouterr().err

    def test_crash_exit_maps_to_crash_at_step(self, tmp_path):
        from pytorch_operator_tpu.faults.record import plan_from_recording

        state = tmp_path / "state"
        key = "default/crash"
        _write_status(state, key, "master-0",
                      _status_beats(100.0, 7, 0.1))
        ev_dir = state / "events"
        ev_dir.mkdir(parents=True, exist_ok=True)
        with open(ev_dir / (key_to_fs(key) + ".events.jsonl"), "a") as f:
            f.write(json.dumps({
                "timestamp": 101.0, "type": "Warning",
                "reason": "TPUJobRestarting",
                "message": "replica default_crash-master-0 failed with "
                           "exit code 9 (restart #1).",
                "count": 1,
            }) + "\n")
        plan = plan_from_recording(state, key)
        crash = next(f for f in plan.faults if f.kind == "crash_at_step")
        assert crash.target == "master-0"
        assert crash.exit_code == 9
        assert crash.at == 8  # last reported step 7 -> crash replays at 8
        # The plan serializes/loads like any hand-written one.
        assert FaultPlan.from_json(plan.to_json()).faults[0].kind == (
            plan.faults[0].kind
        )


# ---- subprocess e2e ----


def _exit_with_job(name, args, annotations=None, backoff=None, alerts=None):
    job = TPUJob(
        metadata=ObjectMeta(name=name, annotations=dict(annotations or {})),
        spec=TPUJobSpec(
            replica_specs={
                ReplicaType.MASTER: ReplicaSpec(
                    replicas=1,
                    restart_policy=RestartPolicy.ON_FAILURE,
                    template=ProcessTemplate(
                        module="pytorch_operator_tpu.workloads.exit_with",
                        args=[str(a) for a in args],
                    ),
                ),
            },
            run_policy=RunPolicy(backoff_limit=backoff),
            observability=(
                ObservabilityPolicy(alerts=alerts) if alerts else None
            ),
        ),
    )
    set_defaults(job)
    return job


def _run_watched(sup, key, timeout=45.0, on_pass=None):
    """Daemon-style passes to completion; ``on_pass(job)`` sampled each
    pass. Returns the final job object (one extra pass runs after the
    finish so the watch finalizes)."""
    deadline = time.time() + timeout
    j = None
    while time.time() < deadline:
        sup.sync_once()
        j = sup.store.get(key)
        if on_pass is not None:
            on_pass(j)
        if j is None or j.is_finished():
            sup.sync_once()  # the finalize pass
            break
        time.sleep(0.03)
    return j


@pytest.mark.chaos
def test_drop_heartbeat_alert_fires_before_deadline_kill(tmp_path, capsys):
    """THE acceptance e2e: under a drop_heartbeat world with a 2s
    hang-deadline, the heartbeat_silence alert reaches ``firing`` —
    visible in the live state, the tpujob_alerts gauge, and the on-disk
    log — strictly BEFORE the TPUJobHung kill; afterward the same alert
    appears resolved and cited in ``tpujob why``, and ``chaos
    --record`` reconstructs the replayable drop_heartbeat plan."""
    from pytorch_operator_tpu.client.cli import main

    faults.disarm()
    state = tmp_path / "state"
    sup = Supervisor(state_dir=state, poll_interval=0.03)
    key = "default/hang-e2e"
    seen = {"firing_before_kill": False}
    try:
        faults.arm(FaultPlan(seed=1, faults=[
            Fault(kind="drop_heartbeat", target="master-0",
                  nth=3, times=100000),
        ]))
        job = _exit_with_job(
            "hang-e2e", ["--steps", "400", "--step-time", "0.05"],
            annotations={HANG_DEADLINE_ANNOTATION: "2"}, backoff=0,
        )
        sup.submit(job)

        def on_pass(j):
            if seen["firing_before_kill"]:
                return
            firing = [
                a for a in sup.watch.active_alerts(key)
                if a.state == "firing" and a.rule == "heartbeat_silence"
            ]
            if firing:
                # The kill has NOT happened yet: the operator saw the
                # alert first.
                assert "TPUJobHung" not in [
                    e.reason for e in sup.events.for_job(key)
                ]
                assert sup.metrics.alerts_firing.get(
                    job=key, rule="heartbeat_silence", severity="critical"
                ) == 1
                assert firing[0].replica == "master-0"
                seen["firing_before_kill"] = True

        j = _run_watched(sup, key, on_pass=on_pass)
        reasons = [e.reason for e in sup.events.for_job(key)]
    finally:
        faults.disarm()
        sup.shutdown()
    assert seen["firing_before_kill"], "alert never fired before the kill"
    assert "TPUJobHung" in reasons
    assert j is not None and j.is_failed()

    # The on-disk log holds the full lifecycle: firing, then resolved
    # (closed by the job's death, not left dangling).
    recs = obs_watch.load_alert_log(state, key)
    states = [r["state"] for r in recs
              if r["rule"] == "heartbeat_silence"]
    assert states == ["firing", "resolved"]

    # `tpujob alerts` renders it (daemon-less, from the log)...
    assert main(["--state-dir", str(state), "alerts"]) == 0
    out = capsys.readouterr().out
    assert "heartbeat_silence" in out
    # ...and the JSON surface carries the transitions.
    assert main(
        ["--state-dir", str(state), "alerts", "hang-e2e", "--json"]
    ) == 0
    records = json.loads(capsys.readouterr().out)
    assert [r["state"] for r in records] == ["firing", "resolved"]

    # `tpujob why` cites the live alerts next to its own finding.
    report = obs_analyze.analyze(state, key)
    assert "heartbeat_silence" in [f["rule"] for f in report["findings"]]
    assert [a["state"] for a in report["alerts"]] == ["firing", "resolved"]
    rendered = obs_analyze.render_report(report)
    assert "LIVE ALERTS" in rendered and "resolved" in rendered

    # `tpujob chaos --record`: the watched incident becomes a plan.
    plan_path = tmp_path / "incident.json"
    assert main(
        ["--state-dir", str(state), "chaos", "hang-e2e", "--record",
         "--out", str(plan_path)]
    ) == 0
    plan = FaultPlan.load(plan_path)
    drop = next(f for f in plan.faults if f.kind == "drop_heartbeat")
    assert drop.target == "master-0"
    assert drop.nth == 3  # 2 beats observed -> silence starts at the 3rd


@pytest.mark.chaos
def test_bounded_drop_resolves_after_recovery(tmp_path):
    """A bounded heartbeat drop (the world recovers on its own): the
    alert fires during the silence and resolves — while the job is
    STILL RUNNING — once beats resume past clear_s."""
    faults.disarm()
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.03)
    key = "default/recover-e2e"
    seen = {"fired": False, "resolved_live": False}
    try:
        faults.arm(FaultPlan(seed=1, faults=[
            Fault(kind="drop_heartbeat", target="master-0",
                  nth=10, times=40),
        ]))
        job = _exit_with_job(
            "recover-e2e", ["--steps", "150", "--step-time", "0.05"],
            alerts=AlertPolicy(clear_s=0.5),
        )
        sup.submit(job)

        def on_pass(j):
            rules = {
                a.rule: a.state for a in sup.watch.active_alerts(key)
            }
            if rules.get("heartbeat_silence") == "firing":
                seen["fired"] = True
            if (
                seen["fired"]
                and "heartbeat_silence" not in rules
                and j is not None
                and not j.is_finished()
            ):
                seen["resolved_live"] = True

        j = _run_watched(sup, key, on_pass=on_pass)
        # The pass-sampled flags: walk the log for the ground truth too.
        recs = obs_watch.load_alert_log(tmp_path / "state", key)
    finally:
        faults.disarm()
        sup.shutdown()
    assert j is not None and j.is_succeeded()
    assert seen["fired"], "the silence alert never fired during the drop"
    states = [r["state"] for r in recs if r["rule"] == "heartbeat_silence"]
    assert states[:2] == ["firing", "resolved"]
    # Resolution came from RECOVERY, not from the job finishing.
    resolved = next(r for r in recs if r["state"] == "resolved")
    assert "(job finished)" not in resolved["summary"]


@pytest.mark.chaos
def test_enospc_world_fires_checkpoint_lag_live(tmp_path):
    """Persistent disk-full after the 3rd save: commits stop, training
    continues — the checkpoint_lag alert fires while the job runs."""
    faults.disarm()
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.03)
    key = "default/enospc-e2e"
    seen = {"lag_fired": False}
    try:
        faults.arm(FaultPlan(seed=1, faults=[
            Fault(kind="enospc_checkpoint_write", target="master-0",
                  nth=4, times=100000),
        ]))
        job = _exit_with_job(
            "enospc-e2e",
            ["--steps", "60", "--step-time", "0.05",
             "--async-checkpoint"],
        )
        sup.submit(job)

        def on_pass(j):
            if any(
                a.rule == "checkpoint_lag" and a.state == "firing"
                for a in sup.watch.active_alerts(key)
            ):
                seen["lag_fired"] = True

        j = _run_watched(sup, key, on_pass=on_pass)
    finally:
        faults.disarm()
        sup.shutdown()
    assert j is not None and j.is_succeeded()
    assert seen["lag_fired"], "checkpoint_lag never fired live"
    recs = obs_watch.load_alert_log(tmp_path / "state", key)
    assert "checkpoint_lag" in [r["rule"] for r in recs]


def test_feed_stalled_world_fires_feed_dominance_live(tmp_path):
    """A world whose heartbeats report a dominant feed stall trips the
    input-bound rule live (no fault plan needed — the workload flag IS
    the stall)."""
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.03)
    key = "default/feed-e2e"
    seen = {"fired": False}
    try:
        job = _exit_with_job(
            "feed-e2e",
            ["--steps", "40", "--step-time", "0.05",
             "--feed-stall-ms", "40"],
        )
        sup.submit(job)

        def on_pass(j):
            if any(
                a.rule == "feed_stall_dominance" and a.state == "firing"
                for a in sup.watch.active_alerts(key)
            ):
                seen["fired"] = True

        j = _run_watched(sup, key, on_pass=on_pass)
    finally:
        sup.shutdown()
    assert j is not None and j.is_succeeded()
    assert seen["fired"], "feed_stall_dominance never fired live"


# ---- bench_smoke: healthy world = all rules, zero alerts, zero I/O ----


@pytest.mark.bench_smoke
def test_healthy_world_evaluates_clean_with_zero_added_io(tmp_path):
    """Acceptance pin: a healthy real-subprocess run under the daemon
    loop EVALUATES the rules (the engine ran) yet raises zero alerts,
    appends zero alert-log lines, and creates no alerts dir at all —
    the live health engine is free when nothing is wrong. (The
    idle-fleet store-I/O pin rides test_ctrlplane_bench.)"""
    sup = Supervisor(state_dir=tmp_path / "state", poll_interval=0.03)
    key = "default/healthy-e2e"
    try:
        job = _exit_with_job(
            "healthy-e2e", ["--steps", "12", "--step-time", "0.03"]
        )
        sup.submit(job)
        j = _run_watched(sup, key)
        evaluations = sup.watch.io.evaluations
        appends = sup.watch.io.log_appends
    finally:
        sup.shutdown()
    assert j is not None and j.is_succeeded()
    assert evaluations > 0, "the watch never ran on a reporting job"
    assert appends == 0
    assert obs_watch.load_alert_log(tmp_path / "state", key) == []
    assert not (tmp_path / "state" / "alerts").exists()
    # And the round-trip probe rode along: the clock log holds at least
    # one round-trip triple (probe file written, echoed, folded).
    from pytorch_operator_tpu.obs.clock import (
        job_clock_log, load_observations,
    )

    obs_pairs = load_observations(
        job_clock_log(tmp_path / "state", key)
    ).get("master-0", [])
    assert any(len(p) == 3 for p in obs_pairs), (
        "no round-trip clock sample recorded"
    )
