"""The train -> checkpoint -> serve journey (generate --restore).

Reference analog: none — the reference orchestrates training pods; what
its users do next (serve the trained weights) is exactly the journey a
complete framework must close. Pins that a llama_train checkpoint
restores into the generate workload WITHOUT reconstructing the training
run's optimizer state, that the trained weights actually flow (tokens
differ from random init and reflect the learned bigram structure), and
that quantized serving composes on top.
"""

from __future__ import annotations

import numpy as np

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.workloads import generate as gen_mod
from pytorch_operator_tpu.workloads import llama_train

import pytest

# Fast-lane exclusion (-m 'not slow'): real train->checkpoint->serve runs.
pytestmark = pytest.mark.slow


def _train_checkpoint(tmp_path, monkeypatch, steps=30):
    ckpt = tmp_path / "ckpt"
    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(ckpt))
    result = llama_train.run(
        config="tiny", batch_size=8, seq_len=32, steps=steps, warmup=1,
        lr=1e-3, checkpoint_every=steps, log=lambda *_: None,
    )
    monkeypatch.delenv("TPUJOB_CHECKPOINT_DIR")
    return ckpt, result


class TestTrainToServe:
    def test_restore_serves_trained_weights(self, tmp_path, monkeypatch):
        ckpt, train_result = _train_checkpoint(tmp_path, monkeypatch)
        assert train_result["final_loss"] < 5.0  # learned past chance

        served = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=8,
            restore=str(ckpt), log=lambda *_: None,
        )
        assert served["restored_step"] == train_result["end_step"]

        fresh = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=8,
            log=lambda *_: None,
        )
        assert "restored_step" not in fresh

        # The trained weights must actually drive generation: greedy
        # rollouts from the learned bigram model continue the synthetic
        # stream (next = 5*tok + 3 mod 256) far better than random init.
        # Check directly via one forward pass of the served params.
        from pytorch_operator_tpu.checkpoint.manager import CheckpointManager
        from pytorch_operator_tpu.models import llama as llama_lib

        with CheckpointManager(ckpt) as mgr:
            _, tree = mgr.restore_tree()
        model = llama_lib.Llama(llama_lib.llama_tiny())
        toks = llama_train.synthetic_bigram_batch(2, 16, 256, step=123)
        logits = np.asarray(model.apply({"params": tree["params"]}, toks))
        pred = logits[:, :-1].argmax(-1)
        want = toks[:, 1:]
        acc = (pred == want).mean()
        # Chance is 1/256; 30 tiny-config steps reach ~70%+. Random
        # init would sit at ~0 — this pins that the TRAINED weights
        # are what came back.
        assert acc > 0.5, acc

    def test_restore_composes_with_quantized_serving(
        self, tmp_path, monkeypatch
    ):
        ckpt, _ = _train_checkpoint(tmp_path, monkeypatch, steps=4)
        served = gen_mod.run(
            config="tiny", batch_size=2, prompt_len=8, max_new_tokens=4,
            restore=str(ckpt), quantize="int8", kv_quantize="int8",
            log=lambda *_: None,
        )
        assert served["quantize"] == "int8"
        assert served["restored_step"] == 5  # 4 steps + 1 warmup

    def test_wrong_config_rejected_with_shape_message(
        self, tmp_path, monkeypatch
    ):
        import pytest

        ckpt, _ = _train_checkpoint(tmp_path, monkeypatch, steps=2)
        with pytest.raises(ValueError, match="embedding"):
            gen_mod.run(
                config="0.3b", batch_size=1, prompt_len=8,
                max_new_tokens=4, restore=str(ckpt), log=lambda *_: None,
            )

    def test_wrong_depth_rejected_with_path_message(
        self, tmp_path, monkeypatch
    ):
        """ADVICE r4: a checkpoint with a MATCHING embedding but a
        different layer stack used to pass the friendly check and die
        inside tracing. The full-structure check must name the first
        mismatching path."""
        import pytest

        from pytorch_operator_tpu.checkpoint.manager import CheckpointManager

        ckpt, _ = _train_checkpoint(tmp_path, monkeypatch, steps=2)
        with CheckpointManager(ckpt) as mgr:
            step, tree = mgr.restore_tree()
        # Same embedding, half the layers: slice the stacked leading
        # (n_layers) dim of every per-layer leaf.
        import jax

        tree["params"]["layers"] = jax.tree.map(
            lambda x: x[:1], tree["params"]["layers"]
        )
        forged = tmp_path / "forged"
        with CheckpointManager(forged) as mgr:
            mgr.save(step, tree)
        with pytest.raises(ValueError, match=r"layers"):
            gen_mod.run(
                config="tiny", batch_size=1, prompt_len=8,
                max_new_tokens=4, restore=str(forged), log=lambda *_: None,
            )

    def test_missing_checkpoint_is_a_clear_error(self, tmp_path):
        import pytest

        with pytest.raises(FileNotFoundError, match="no checkpoint"):
            gen_mod.run(
                config="tiny", batch_size=1, prompt_len=8,
                max_new_tokens=4, restore=str(tmp_path / "nope"),
                log=lambda *_: None,
            )
