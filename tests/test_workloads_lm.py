"""LM workload tests: BERT-FSDP fine-tune and Llama train, in-process on
the 8-device CPU mesh — learning actually happens, optimizer state is
really ZeRO-sharded, and checkpoint resume continues rather than restarts.
"""

import os

import pytest

import tests.jaxenv  # noqa: F401

from pytorch_operator_tpu.workloads import bert_fsdp, llama_train

# Fast-lane exclusion (-m 'not slow'): full llama workload runs (resume/accum/optimizers).
pytestmark = pytest.mark.slow


def test_bert_fsdp_learns_and_shards_opt_state():
    import jax
    import numpy as np
    import optax

    from pytorch_operator_tpu.models.bert import BertClassifier, bert_tiny
    from pytorch_operator_tpu.parallel import make_mesh
    from pytorch_operator_tpu.workloads.trainer import init_sharded_train_state

    # The ZeRO claim, asserted directly: Adam mu/nu leaves carry the fsdp
    # sharding of their params.
    mesh = make_mesh({"fsdp": 8})
    model = BertClassifier(bert_tiny(), num_classes=2)
    tx = optax.adamw(1e-4)
    state, _ = init_sharded_train_state(
        lambda k: model.init(k, np.zeros((1, 16), np.int32)), tx, mesh
    )
    mu = state["opt_state"][0].mu
    q_mu = mu["bert"]["layers"]["attn"]["q_proj"]["kernel"]
    q_p = state["params"]["bert"]["layers"]["attn"]["q_proj"]["kernel"]
    assert q_mu.sharding == q_p.sharding
    assert "fsdp" in tuple(q_mu.sharding.spec)

    result = bert_fsdp.run(
        mesh_spec="fsdp=8", batch_size=32, seq_len=32, steps=40, warmup=1,
        lr=3e-4, log=lambda *_: None,
    )
    assert result["final_accuracy"] >= 0.9, result
    assert result["final_loss"] < 0.5, result


def test_llama_train_loss_decreases():
    result = llama_train.run(
        config="tiny", mesh_spec="dp=2,fsdp=2,tp=2", batch_size=8, seq_len=32,
        steps=25, warmup=1, lr=1e-3, log=lambda *_: None,
    )
    # ln(256) ≈ 5.55 is chance level on the synthetic bigram stream.
    assert result["final_loss"] < 5.0, result


def test_donation_and_remat_policy_do_not_change_numerics():
    """State donation and the 'dots' selective-remat policy are pure
    execution-strategy knobs — the loss trajectory must be bit-identical
    to the default path (same graph, different buffer/residual plans)."""
    runs = {}
    for tag, kw in {
        "control": dict(donate=False),
        "donated": dict(donate=True),
        "dots": dict(donate=True, remat=True, remat_policy="dots"),
        "full": dict(donate=True, remat=True, remat_policy="full"),
    }.items():
        runs[tag] = llama_train.run(
            config="tiny", batch_size=4, seq_len=32, steps=8, warmup=1,
            log=lambda *_: None, **kw,
        )["final_loss"]
    assert len(set(runs.values())) == 1, runs


def test_adafactor_trains_with_factored_state():
    """--optimizer adafactor must learn AND actually carry factored
    second moments (state ~N/k floats, not AdamW's 2N) — the memory
    lever at LM scale."""
    import jax

    from pytorch_operator_tpu.parallel import make_mesh
    from pytorch_operator_tpu.workloads.trainer import (
        init_sharded_train_state,
        make_optimizer,
    )

    # Adafactor's normalized updates want a higher LR than AdamW's 3e-4.
    result = llama_train.run(
        config="tiny", batch_size=8, seq_len=32, steps=40, warmup=1,
        lr=1e-1, optimizer="adafactor", log=lambda *_: None,
    )
    assert result["final_loss"] < 5.0, result

    # State-size claim, measured: count optimizer floats for both.
    from pytorch_operator_tpu.models.llama import Llama, llama_tiny
    import numpy as np

    mesh = make_mesh("dp=-1")
    model = Llama(llama_tiny(), mesh=mesh)

    def count(opt_name):
        tx = make_optimizer(1e-3, optimizer=opt_name)
        state, _ = init_sharded_train_state(
            lambda k: model.init(k, np.zeros((1, 32), np.int32)), tx, mesh
        )
        return sum(x.size for x in jax.tree.leaves(state["opt_state"]))

    adamw, adafactor = count("adamw"), count("adafactor")
    assert adafactor < adamw / 1.5, (adamw, adafactor)


def test_grad_accum_matches_unsplit_step():
    """grad_accum=N (sequential microbatches, mean grads, one update)
    must reproduce the unsplit step's loss trajectory up to f32
    reassociation — same global batch, ~N-fold less activation memory."""
    losses = {
        n: llama_train.run(
            config="tiny", batch_size=8, seq_len=32, steps=6, warmup=1,
            grad_accum=n, log=lambda *_: None,
        )["final_loss"]
        for n in (1, 2, 4)
    }
    assert losses[2] == pytest.approx(losses[1], abs=2e-3), losses
    assert losses[4] == pytest.approx(losses[1], abs=2e-3), losses


def test_grad_accum_on_pp_mesh_refused():
    with pytest.raises(ValueError, match="grad_accum.*pp"):
        llama_train.run(
            config="tiny", mesh_spec="dp=4,pp=2", batch_size=8, seq_len=32,
            steps=2, grad_accum=2, log=lambda *_: None,
        )


def test_remat_policy_without_remat_refused():
    with pytest.raises(ValueError, match="no effect without --remat"):
        llama_train.run(
            config="tiny", batch_size=2, seq_len=16, steps=2,
            remat_policy="dots", log=lambda *_: None,
        )


def test_donate_composes_with_async_checkpoint(tmp_path, monkeypatch):
    """save(block=False) snapshots the state to host BEFORE returning
    (checkpoint/async_writer.py), so donation no longer tears in-flight
    commits: the donated run's async-saved steps must all verify."""
    from pytorch_operator_tpu.checkpoint import CheckpointManager

    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path))
    llama_train.run(
        config="tiny", batch_size=2, seq_len=16, steps=3, warmup=1,
        checkpoint_every=2, async_checkpoint=True, donate=True,
        log=lambda *_: None,
    )
    with CheckpointManager(tmp_path, create=False) as mgr:
        steps = mgr.all_steps()
        assert steps, "async run committed no checkpoints"
        # Sidecar-at-commit: the newest VERIFIED step is the newest step.
        assert mgr.latest_verified_step() == steps[-1]


def test_prefetched_feed_is_batch_for_batch_identical(tmp_path):
    """--prefetch must not change WHAT trains, only WHERE the transfer
    happens: the double-buffered feed produces the same batch sequence
    as the inline path, so two same-seed runs land the same final
    loss."""
    from pytorch_operator_tpu.workloads import llama_train

    kw = dict(
        config="tiny", mesh_spec="dp=8", batch_size=8, seq_len=32,
        steps=3, warmup=1, log=lambda *_: None,
    )
    inline = llama_train.run(**kw)
    prefetched = llama_train.run(prefetch=2, **kw)
    assert prefetched["final_loss"] == pytest.approx(
        inline["final_loss"], abs=1e-5
    )


def test_llama_trains_from_packed_text_file(tmp_path):
    """The real-data LM path: a text file packed byte-level streams
    through the prefetch loader into training, with the cosine schedule
    and gradient clipping active."""
    import numpy as np

    from pytorch_operator_tpu.data import pack_arrays
    from pytorch_operator_tpu.workloads import llama_train

    # Learnable corpus: shifted arithmetic sequences (next = cur + 1
    # mod 256), so a few steps drive the loss well below chance.
    tokens = (
        (np.arange(96)[None, :] + np.arange(64)[:, None]) % 256
    ).astype(np.int32)
    f = tmp_path / "toks.bin"
    pack_arrays(f, {"tokens": tokens})

    result = llama_train.run(
        config="tiny",
        mesh_spec="dp=8",
        batch_size=8,
        seq_len=64,  # records hold 96 — sliced
        steps=20,
        warmup=1,
        lr=3e-3,
        data_file=str(f),
        lr_schedule="cosine",
        lr_warmup_steps=2,
        grad_clip=1.0,
        log=lambda *_: None,
    )
    assert np.isfinite(result["final_loss"])
    assert result["final_loss"] < 5.0  # well below chance (ln 256 ≈ 5.55)


def test_llama_eval_file_reports_heldout_loss(tmp_path):
    """--eval-file computes held-out loss + perplexity with the training
    objective, no updates; on a learnable corpus the trained model's eval
    loss lands below chance."""
    import numpy as np

    from pytorch_operator_tpu.data import pack_arrays

    tokens = (
        (np.arange(48)[None, :] + np.arange(64)[:, None]) % 256
    ).astype(np.int32)
    train_f, eval_f = tmp_path / "train.bin", tmp_path / "eval.bin"
    pack_arrays(train_f, {"tokens": tokens})
    pack_arrays(eval_f, {"tokens": (tokens + 1) % 256})

    result = llama_train.run(
        config="tiny", mesh_spec="dp=8", batch_size=8, seq_len=48,
        steps=20, warmup=1, lr=3e-3, data_file=str(train_f),
        eval_file=str(eval_f), eval_batches=2, log=lambda *_: None,
    )
    assert np.isfinite(result["eval_loss"])
    assert result["eval_loss"] < 5.55  # below ln(256) chance
    # Both fields are rounded for the JSON line — relative tolerance
    # covers the rounding at any loss magnitude.
    assert result["eval_perplexity"] == pytest.approx(
        np.exp(result["eval_loss"]), rel=2e-2
    )


def test_llama_data_file_validation(tmp_path):
    import numpy as np
    import pytest

    from pytorch_operator_tpu.data import pack_arrays
    from pytorch_operator_tpu.workloads import llama_train

    # Wrong field name.
    f1 = tmp_path / "imgs.bin"
    pack_arrays(f1, {"x": np.zeros((8, 4), np.float32)})
    with pytest.raises(ValueError, match="tokens"):
        llama_train.run(
            config="tiny", mesh_spec="dp=8", batch_size=8, seq_len=4,
            steps=1, warmup=1, data_file=str(f1), log=lambda *_: None,
        )
    # Token ids past the model vocab.
    f2 = tmp_path / "big.bin"
    pack_arrays(
        f2, {"tokens": np.full((8, 16), 9999, np.int32)}
    )
    with pytest.raises(ValueError, match="vocab"):
        llama_train.run(
            config="tiny", mesh_spec="dp=8", batch_size=8, seq_len=16,
            steps=1, warmup=1, data_file=str(f2), log=lambda *_: None,
        )
    # Negative ids clamp as silently as too-large ones — also rejected.
    f3 = tmp_path / "neg.bin"
    toks = np.zeros((8, 16), np.int32)
    toks[3, 7] = -5
    pack_arrays(f3, {"tokens": toks})
    with pytest.raises(ValueError, match="vocab"):
        llama_train.run(
            config="tiny", mesh_spec="dp=8", batch_size=8, seq_len=16,
            steps=1, warmup=1, data_file=str(f3), log=lambda *_: None,
        )


def test_llama_data_file_resume_fast_forwards(tmp_path, monkeypatch):
    """A resumed --data-file run must not replay already-consumed
    batches: the loader fast-forwards to start_step."""
    import numpy as np

    from pytorch_operator_tpu.data import pack_arrays

    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path / "ck"))
    tokens = (
        (np.arange(48)[None, :] + np.arange(64)[:, None]) % 256
    ).astype(np.int32)
    f = tmp_path / "toks.bin"
    pack_arrays(f, {"tokens": tokens})
    kw = dict(
        config="tiny", mesh_spec="dp=8", batch_size=8, seq_len=32,
        steps=4, warmup=1, checkpoint_every=3, data_file=str(f),
    )
    llama_train.run(**kw, log=lambda *_: None)
    logs = []
    llama_train.run(**kw, log=logs.append)
    assert any("resumed from checkpoint" in m for m in logs), logs
    assert any("fast-forwarded" in m for m in logs), logs


def test_llama_checkpoint_resume(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path / "ck"))
    r1 = llama_train.run(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=4, warmup=1, checkpoint_every=3, log=lambda *_: None,
    )
    logs = []
    r2 = llama_train.run(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=4, warmup=1, checkpoint_every=3, log=logs.append,
    )
    assert any("resumed from checkpoint" in m for m in logs), logs
    assert r2["end_step"] == r1["end_step"] + 5  # warmup(1) + steps(4)


def test_llama_async_checkpoint_resume(tmp_path, monkeypatch):
    """Async saves must still be durable by job end (mgr.close commits),
    so a follow-up run resumes exactly like the blocking path."""
    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path / "ck"))
    r1 = llama_train.run(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=4, warmup=1, checkpoint_every=3, async_checkpoint=True,
        log=lambda *_: None,
    )
    logs = []
    r2 = llama_train.run(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=4, warmup=1, checkpoint_every=3, async_checkpoint=True,
        log=logs.append,
    )
    assert any("resumed from checkpoint" in m for m in logs), logs
    assert r2["end_step"] == r1["end_step"] + 5


def test_llama_cosine_resume_without_horizon_warns(tmp_path, monkeypatch):
    """ADVICE r2: with --lr-schedule cosine and no --max-steps /
    --lr-decay-steps the decay horizon defaults to this LIFE's steps, so
    a resumed run (global optimizer count) trains its whole tail at
    LR~0 — detectable at resume time, so it must warn."""
    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path / "ck"))
    kw = dict(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=4, warmup=1, checkpoint_every=3, lr_schedule="cosine",
    )
    logs = []
    llama_train.run(**kw, log=logs.append)
    assert not any("LR~0" in m for m in logs), logs  # fresh run: no warning
    logs = []
    llama_train.run(**kw, log=logs.append)
    assert any("resumed from checkpoint" in m for m in logs), logs
    assert any("LR~0" in m for m in logs), logs
    # An explicit global horizon silences it.
    logs = []
    llama_train.run(**kw, lr_decay_steps=64, log=logs.append)
    assert not any("LR~0" in m for m in logs), logs


def test_llama_max_steps_caps_work(tmp_path, monkeypatch):
    monkeypatch.setenv("TPUJOB_CHECKPOINT_DIR", str(tmp_path / "ck"))
    r1 = llama_train.run(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=10, warmup=1, checkpoint_every=4, max_steps=6,
        log=lambda *_: None,
    )
    assert r1["end_step"] == 6
    # resumed run respects the cap: only the remainder is run
    r2 = llama_train.run(
        config="tiny", mesh_spec="fsdp=8", batch_size=8, seq_len=32,
        steps=10, warmup=1, checkpoint_every=4, max_steps=8,
        log=lambda *_: None,
    )
    assert r2["end_step"] == 8


def test_llama_1b_plan_fits_one_v5e_chip():
    """The MFU-vs-scale config (BASELINE.md round-4): ~1.14B params, and
    its measured on-chip recipe — bf16 params + adafactor + batch 2 —
    must fit v5e HBM with the 'dots'-remat residuals. Abstract
    (eval_shape): no compile, no arrays."""
    import math

    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from pytorch_operator_tpu.models import llama as llama_lib

    cfg = llama_lib.llama_1b(param_dtype=jnp.bfloat16)
    model = llama_lib.Llama(cfg)
    tx = optax.adafactor(1e-3)

    def abstract_state(key):
        params = model.init(key, np.zeros((1, 32), np.int32))["params"]
        return {"params": params, "opt_state": tx.init(params)}

    abstract = jax.eval_shape(abstract_state, jax.random.key(0))
    n_params = sum(
        math.prod(x.shape) for x in jax.tree.leaves(abstract["params"])
    )
    assert 1.0e9 < n_params < 1.3e9, f"param count {n_params/1e9:.2f}B"

    state_bytes = sum(
        math.prod(x.shape) * jnp.dtype(x.dtype).itemsize
        for x in jax.tree.leaves(abstract)
    )
    # bf16 params + factored adafactor stats ~= 2.5 GiB; grads (bf16,
    # transient) + batch-2 'dots' residuals (~7 GiB measured headroom)
    # keep the whole step under v5e's 16 GiB — the measured recipe.
    assert state_bytes < 4 * 2**30, f"state {state_bytes/2**30:.1f} GiB"
