"""Deterministic fault injection (faults/) and the failure-path
hardening it forces.

Fast lane (tier-1, ``chaos`` marker): plan parsing, injector
determinism, every controller-side site against the FakeRunner, the
hung-world detector, and ONE full end-to-end chaos replay — worker
crash at an exact step + a failed checkpoint write + a torn checkpoint
write + a rendezvous stall, run twice through ``tpujob chaos`` with
real subprocess casualties, asserting exactly-once completion, restore
from the last verified-good step, and byte-identical replay summaries.

The wider crash-step x stall matrix is marked ``slow``.
"""

import json
import os
import time

import pytest

from pytorch_operator_tpu import faults
from pytorch_operator_tpu.api import ReplicaPhase, ReplicaType
from pytorch_operator_tpu.api.defaults import HANG_DEADLINE_ANNOTATION
from pytorch_operator_tpu.controller import (
    EventRecorder,
    FakeRunner,
    GangScheduler,
    JobStore,
    MetricsRegistry,
    Reconciler,
    replica_name,
)
from pytorch_operator_tpu.controller.store import key_to_fs
from pytorch_operator_tpu.controller.supervisor import Supervisor
from pytorch_operator_tpu.faults import Fault, FaultPlan
from tests.testutil import new_job

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with no plan armed and a cold
    worker-side cache (the cache pins the env read)."""
    faults.disarm()
    yield
    faults.disarm()


def make_harness(status_root=None):
    store = JobStore()
    runner = FakeRunner()
    events = EventRecorder()
    rec = Reconciler(
        store=store,
        runner=runner,
        events=events,
        metrics=MetricsRegistry(),
        gang=GangScheduler(enabled=True),
        status_root=status_root,
    )
    return store, runner, events, rec


def reasons(events, key):
    return [e.reason for e in events.for_job(key)]


# ---- plan serialization ----


class TestFaultPlan:
    def test_roundtrip_dict_json_env(self):
        plan = FaultPlan(
            seed=7,
            faults=[
                Fault(kind="crash_at_step", target="worker-1", at=5, exit_code=3),
                Fault(kind="fail_checkpoint_write", nth=2, times=2),
                Fault(kind="stall_rendezvous", seconds=1.5, restart=0),
            ],
        )
        assert FaultPlan.from_dict(plan.to_dict()).to_json() == plan.to_json()
        assert FaultPlan.from_json(plan.to_json()).to_json() == plan.to_json()
        env = {"TPUJOB_FAULT_PLAN": plan.to_env()}
        assert FaultPlan.from_env(env).to_json() == plan.to_json()
        assert FaultPlan.from_env({}) is None

    def test_yaml_file_load(self, tmp_path):
        p = tmp_path / "plan.yaml"
        p.write_text(
            "seed: 3\nfaults:\n"
            "  - {kind: kill_replica, target: worker-0, at: 4}\n"
        )
        plan = FaultPlan.load(p)
        assert plan.seed == 3
        assert plan.faults[0].kind == "kill_replica"
        # from_env accepts a file reference too.
        assert (
            FaultPlan.from_env({"TPUJOB_FAULT_PLAN": f"@{p}"}).to_json()
            == plan.to_json()
        )

    def test_rejects_unknown_kind_and_fields(self):
        with pytest.raises(ValueError):
            Fault(kind="meteor_strike")
        with pytest.raises(ValueError):
            Fault.from_dict({"kind": "kill_replica", "color": "red"})

    def test_summary_is_deterministic(self):
        plan = FaultPlan(seed=1, faults=[Fault(kind="kill_replica", at=2)])
        assert plan.summary() == plan.summary()
        assert "kill_replica" in plan.summary()


class TestPlanValidation:
    """Plan lint against a job spec: targets that can never match warn."""

    def _validate(self, plan, job):
        from pytorch_operator_tpu.faults.plan import validate_against_job

        return validate_against_job(plan, job)

    def test_matching_targets_produce_no_warnings(self):
        plan = FaultPlan(
            faults=[
                Fault(kind="crash_at_step", target="worker-1", at=3),
                Fault(kind="kill_replica", target="master-*", at=2),
                Fault(kind="stall_rendezvous", target="*"),
            ]
        )
        assert self._validate(plan, new_job(workers=2)) == []

    def test_out_of_range_index_warns(self):
        plan = FaultPlan(
            faults=[Fault(kind="crash_at_step", target="worker-3", at=1)]
        )
        warnings = self._validate(plan, new_job(workers=2))
        assert len(warnings) == 1
        assert "worker-3" in warnings[0]
        assert "never fire" in warnings[0]

    def test_wrong_type_name_warns(self):
        plan = FaultPlan(
            faults=[Fault(kind="kill_replica", target="wrker-0", at=1)]
        )
        assert len(self._validate(plan, new_job(workers=1))) == 1

    def test_elastic_targets_validated_to_max_replicas(self):
        from pytorch_operator_tpu.api.types import ElasticPolicy

        job = new_job(
            workers=1,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=4),
        )
        plan = FaultPlan(
            faults=[Fault(kind="kill_replica", target="worker-3", at=1)]
        )
        # worker-3 may exist after an elastic grow: not a lint error.
        assert self._validate(plan, job) == []
        plan_bad = FaultPlan(
            faults=[Fault(kind="kill_replica", target="worker-4", at=1)]
        )
        assert len(self._validate(plan_bad, job)) == 1

    def test_job_scoped_target_checked_against_key(self):
        job = new_job(name="torny")
        ok = FaultPlan(
            faults=[Fault(kind="torn_state_write", target="default/torny")]
        )
        assert self._validate(ok, job) == []
        bad = FaultPlan(
            faults=[Fault(kind="torn_state_write", target="default/other")]
        )
        assert len(self._validate(bad, job)) == 1

    def test_untargeted_kinds_never_warn(self):
        plan = FaultPlan(
            faults=[Fault(kind="fail_engine_step", target="anything", nth=2)]
        )
        assert self._validate(plan, new_job(workers=0)) == []

    # ---- PR-11 kinds: preempt_replica / kill_storm ----

    def test_preempt_replica_out_of_range_warns(self):
        plan = FaultPlan(
            faults=[Fault(kind="preempt_replica", target="worker-5", at=1)]
        )
        warnings = self._validate(plan, new_job(workers=2))
        assert len(warnings) == 1
        assert "worker-5" in warnings[0]

    def test_preempt_replica_in_range_is_clean(self):
        plan = FaultPlan(
            faults=[Fault(kind="preempt_replica", target="worker-1", at=1)]
        )
        assert self._validate(plan, new_job(workers=2)) == []

    def test_kill_storm_times_beyond_gang_warns_even_for_star(self):
        # workers=2 + 1 master = 3 replicas; a width-8 storm on "*" can
        # never reach its advertised width.
        plan = FaultPlan(
            faults=[Fault(kind="kill_storm", target="*", at=1, times=8)]
        )
        warnings = self._validate(plan, new_job(workers=2))
        assert len(warnings) == 1
        assert "times=8" in warnings[0]

    def test_kill_storm_times_counts_only_matching_replicas(self):
        plan = FaultPlan(
            faults=[
                Fault(kind="kill_storm", target="worker-*", at=1, times=3)
            ]
        )
        warnings = self._validate(plan, new_job(workers=2))
        assert len(warnings) == 1
        assert "worker-*" in warnings[0]

    def test_kill_storm_within_gang_is_clean(self):
        plan = FaultPlan(
            faults=[Fault(kind="kill_storm", target="*", at=1, times=3)]
        )
        assert self._validate(plan, new_job(workers=2)) == []

    def test_kill_storm_counts_elastic_max_replicas(self):
        from pytorch_operator_tpu.api.types import ElasticPolicy

        job = new_job(
            workers=2,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=4),
        )
        # 4 elastic workers + 1 master: width 5 is reachable post-grow.
        plan = FaultPlan(
            faults=[Fault(kind="kill_storm", target="*", at=1, times=5)]
        )
        assert self._validate(plan, job) == []

    def test_chaos_cli_prints_the_warning(self, tmp_path, capsys):
        """`tpujob chaos` surfaces the lint on stderr before running."""
        from pytorch_operator_tpu.client import cli

        job = tmp_path / "job.yaml"
        job.write_text(CHAOS_JOB)
        plan = tmp_path / "plan.yaml"
        plan.write_text(
            "faults:\n  - {kind: crash_at_step, target: worker-9, at: 1}\n"
        )
        rc = cli.main(
            [
                "--state-dir", str(tmp_path / "state"),
                "chaos", str(job),
                "--plan", str(plan),
                "--timeout", "60",
            ]
        )
        err = capsys.readouterr().err
        assert "warning: fault plan" in err and "worker-9" in err
        assert rc == 0  # lint warns; the run itself proceeds


# ---- injector semantics ----


class TestInjector:
    def test_same_plan_same_decisions(self):
        plan = FaultPlan(
            faults=[
                Fault(kind="crash_at_step", target="worker-0", at=3),
                Fault(kind="fail_checkpoint_write", nth=2),
            ]
        )

        def trace(inj):
            out = []
            for step in range(1, 6):
                out.append(inj.crash_exit_code(step, "Worker", 0, 0))
                out.append(inj.checkpoint_write_fault("Worker", 0, 0))
            return out

        assert trace(faults.FaultInjector(plan)) == trace(
            faults.FaultInjector(plan)
        )

    def test_times_budget_and_consumption(self):
        inj = faults.FaultInjector(
            FaultPlan(faults=[Fault(kind="drop_heartbeat", times=2)])
        )
        assert inj.drop_heartbeat("Worker", 0) is True
        assert inj.drop_heartbeat("Worker", 0) is True
        assert inj.drop_heartbeat("Worker", 0) is False
        # drop_heartbeat is an NTH_KIND: its label carries the
        # occurrence window, not a step index.
        assert inj.fired == ["drop_heartbeat(*#1)"] * 2

    def test_drop_heartbeat_nth_window(self):
        """nth > 1 lets the first beats through — the hang-deadline
        chaos scenario trains visibly, THEN goes silent."""
        inj = faults.FaultInjector(
            FaultPlan(faults=[Fault(kind="drop_heartbeat", nth=3, times=2)])
        )
        drops = [inj.drop_heartbeat("Master", 0) for _ in range(6)]
        assert drops == [False, False, True, True, False, False]

    def test_target_and_restart_gating(self):
        plan = FaultPlan(
            faults=[
                Fault(kind="crash_at_step", target="worker-1", at=2, restart=0)
            ]
        )
        inj = faults.FaultInjector(plan)
        assert inj.crash_exit_code(2, "Worker", 0, 0) is None  # wrong index
        assert inj.crash_exit_code(2, "Worker", 1, 1) is None  # wrong life
        assert inj.crash_exit_code(2, "Worker", 1, 0) == 9
        # Consumed: the restart it caused cannot re-crash.
        assert inj.crash_exit_code(2, "Worker", 1, 0) is None

    def test_nth_occurrence_window(self):
        inj = faults.FaultInjector(
            FaultPlan(faults=[Fault(kind="fail_engine_step", nth=2, times=2)])
        )
        fires = [inj.engine_step_fault() is not None for _ in range(5)]
        assert fires == [False, True, True, False, False]

    def test_engine_step_check_raises(self):
        faults.arm(FaultPlan(faults=[Fault(kind="fail_engine_step", nth=2)]))
        faults.engine_step_check()  # occurrence 1: quiet
        with pytest.raises(faults.InjectedFault):
            faults.engine_step_check()
        faults.engine_step_check()  # budget spent: quiet again


# ---- controller-side sites (FakeRunner) ----


class TestControllerSites:
    def test_runner_threads_plan_into_replica_env(self):
        store, runner, events, rec = make_harness()
        faults.arm(FaultPlan(faults=[Fault(kind="crash_at_step", at=1)]))
        key = store.add(new_job(workers=1))
        rec.sync(key)
        env = runner.envs[replica_name(key, ReplicaType.MASTER, 0)]
        assert faults.ENV_VAR in env
        assert FaultPlan.from_env(env).faults[0].kind == "crash_at_step"

    def test_no_plan_no_env(self):
        store, runner, events, rec = make_harness()
        key = store.add(new_job(workers=0))
        rec.sync(key)
        env = runner.envs[replica_name(key, ReplicaType.MASTER, 0)]
        assert faults.ENV_VAR not in env

    def test_fail_spawn_is_retryable(self):
        store, runner, events, rec = make_harness()
        faults.arm(
            FaultPlan(faults=[Fault(kind="fail_spawn", target="master-0")])
        )
        key = store.add(new_job(workers=0))
        rec.sync(key)
        h = runner.get(replica_name(key, ReplicaType.MASTER, 0))
        assert h.phase == ReplicaPhase.FAILED
        assert h.exit_code == 137
        rec.sync(key)  # classify: retryable -> restart spent
        assert store.get(key).status.restart_count == 1
        rec.sync(key)  # respawn: fault budget exhausted -> real create
        h = runner.get(replica_name(key, ReplicaType.MASTER, 0))
        assert h.phase == ReplicaPhase.PENDING

    def test_supervisor_pass_kill(self, tmp_state_dir):
        sup = Supervisor(
            state_dir=tmp_state_dir, runner=FakeRunner(), persist=False
        )
        faults.arm(
            FaultPlan(
                faults=[Fault(kind="kill_replica", target="worker-0", at=2)]
            )
        )
        key = sup.submit(new_job(workers=2))
        sup.sync_once()  # pass 1: world created
        sup.runner.set_all_running(key)
        wname = replica_name(key, ReplicaType.WORKER, 0)
        sup.sync_once()  # pass 2: injected kill + classification
        assert "FaultInjected" in reasons(sup.events, key)
        h = sup.runner.get(wname)
        # Killed 137 (observed FAILED by the same pass's sync -> the
        # restart path ran) or already respawned — either way the job
        # spent exactly one restart on a retryable signal death.
        assert sup.store.get(key).status.restart_count == 1
        assert h is None or h.exit_code in (None, 137)

    def test_torn_state_write_recovery(self, tmp_path):
        persist = tmp_path / "jobs"
        store = JobStore(persist_dir=persist)
        job = new_job(name="torn")
        key = f"default/{job.metadata.name}"
        faults.arm(FaultPlan(faults=[Fault(kind="torn_state_write", target=key)]))
        store.add(job)
        # The torn write landed a half JSON at the real path.
        raw = (persist / (key_to_fs(key) + ".json")).read_text()
        with pytest.raises(ValueError):
            json.loads(raw)
        # A fresh reader (cross-process observer / restarted daemon)
        # skips the corrupt file and surfaces it as a job event.
        events = EventRecorder()
        store2 = JobStore(persist_dir=persist, events=events)
        assert store2.get(key) is None
        assert "CorruptStateFile" in reasons(events, key)
        # The owning store's in-memory object is still authoritative.
        assert store.get(key) is not None

    def test_stale_tmp_sweep_event(self, tmp_path):
        persist = tmp_path / "jobs"
        persist.mkdir(parents=True)
        stale = persist / "default_old.json.1234.tmp"
        stale.write_text("{")
        old = time.time() - 3600
        os.utime(stale, (old, old))
        events = EventRecorder()
        store = JobStore(persist_dir=persist, events=events)
        assert not stale.exists()
        assert "StaleTmpSwept" in reasons(events, "default/old")
        # Off the every-pass path: a tmp file appearing later is NOT
        # swept by the next rescan (the periodic interval gates it)...
        late = persist / "default_late.json.99.tmp"
        late.write_text("{")
        os.utime(late, (old, old))
        store.rescan()
        assert late.exists()
        # ...but a rescan after the interval elapses sweeps it, counting
        # through the same event surface.
        store._last_sweep = time.time() - 10_000
        store.rescan()
        assert not late.exists()
        assert "StaleTmpSwept" in reasons(events, "default/late")


# ---- worker-side sites ----


class TestWorkerSites:
    def _worker_env(self, monkeypatch, plan, status_dir):
        monkeypatch.setenv("TPUJOB_FAULT_PLAN", plan.to_env())
        monkeypatch.setenv("TPUJOB_REPLICA_TYPE", "Master")
        monkeypatch.setenv("TPUJOB_REPLICA_INDEX", "0")
        monkeypatch.setenv("TPUJOB_RESTART_COUNT", "0")
        monkeypatch.setenv("TPUJOB_STATUS_DIR", str(status_dir))

    def test_drop_heartbeat_suppresses_reports(self, monkeypatch, tmp_path):
        from pytorch_operator_tpu.runtime import rendezvous

        plan = FaultPlan(
            faults=[Fault(kind="drop_heartbeat", target="master-0", times=2)]
        )
        self._worker_env(monkeypatch, plan, tmp_path)
        for step in (1, 2, 3, 4):
            rendezvous.report_progress(step)
        recs = [
            json.loads(line)
            for line in (tmp_path / "master-0.jsonl").read_text().splitlines()
        ]
        assert [r["step"] for r in recs] == [3, 4]  # first two dropped

    def test_stall_site_sleeps_and_reports(self, monkeypatch, tmp_path):
        from pytorch_operator_tpu.runtime import rendezvous

        plan = FaultPlan(
            faults=[
                Fault(kind="stall_rendezvous", target="master-0", seconds=0.05)
            ]
        )
        self._worker_env(monkeypatch, plan, tmp_path)
        t0 = time.monotonic()
        assert rendezvous.fault_stall_if_armed() == 0.05
        assert time.monotonic() - t0 >= 0.05
        assert rendezvous.fault_stall_if_armed() == 0.0  # consumed
        recs = (tmp_path / "master-0.jsonl").read_text()
        assert "fault_stall" in recs


# ---- hung-world detection ----


class TestHungWorld:
    def _running_master(self, rec, store, runner, job):
        key = store.add(job)
        rec.sync(key)
        h = runner.get(replica_name(key, ReplicaType.MASTER, 0))
        h.phase = ReplicaPhase.RUNNING
        return key, h

    def test_silent_world_is_killed_and_restarted(self, tmp_path):
        store, runner, events, rec = make_harness(status_root=tmp_path / "s")
        job = new_job(workers=0)
        job.metadata.annotations[HANG_DEADLINE_ANNOTATION] = "30"
        key, h = self._running_master(rec, store, runner, job)
        now = time.time()
        h.created_at = now - 100  # spawned long ago, never heartbeat
        rec.sync(key, now=now)
        assert "TPUJobHung" in reasons(events, key)
        assert store.get(key).status.restart_count == 1
        assert runner.get(replica_name(key, ReplicaType.MASTER, 0)) is None

    def test_fresh_heartbeat_holds_the_kill(self, tmp_path):
        status_root = tmp_path / "s"
        store, runner, events, rec = make_harness(status_root=status_root)
        job = new_job(workers=0)
        job.metadata.annotations[HANG_DEADLINE_ANNOTATION] = "30"
        key, h = self._running_master(rec, store, runner, job)
        now = time.time()
        h.created_at = now - 100
        d = status_root / key_to_fs(key)
        d.mkdir(parents=True, exist_ok=True)
        (d / "master-0.jsonl").write_text(
            json.dumps({"event": "progress", "step": 5, "ts": now - 5}) + "\n"
        )
        rec.sync(key, now=now)
        assert "TPUJobHung" not in reasons(events, key)
        assert store.get(key).status.restart_count == 0

    def test_no_annotation_never_kills(self, tmp_path):
        store, runner, events, rec = make_harness(status_root=tmp_path / "s")
        key, h = self._running_master(rec, store, runner, new_job(workers=0))
        h.created_at = time.time() - 10_000
        rec.sync(key)
        assert "TPUJobHung" not in reasons(events, key)

    def test_backoff_exhausted_fails_the_job(self, tmp_path):
        store, runner, events, rec = make_harness(status_root=tmp_path / "s")
        job = new_job(workers=0, backoff_limit=0)
        job.metadata.annotations[HANG_DEADLINE_ANNOTATION] = "30"
        key, h = self._running_master(rec, store, runner, job)
        h.created_at = time.time() - 100
        rec.sync(key)
        job = store.get(key)
        assert job.is_finished() and not job.is_succeeded()
        assert "TPUJobHung" in reasons(events, key)
        assert job.status.completion_time is not None


# ---- the end-to-end chaos replay (real subprocess casualties) ----

CHAOS_JOB = """\
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: chaos-e2e
spec:
  replica_specs:
    Master:
      replicas: 1
      restart_policy: OnFailure
      template:
        module: pytorch_operator_tpu.workloads.exit_with
        args: ["--steps", "6", "--step-time", "0.05"]
  run_policy:
    backoff_limit: 3
"""

CHAOS_PLAN = """\
seed: 42
faults:
  - {kind: stall_rendezvous, target: master-0, seconds: 0.3, restart: 0}
  - {kind: fail_checkpoint_write, target: master-0, nth: 2, restart: 0}
  - {kind: torn_checkpoint_write, target: master-0, nth: 3, restart: 0}
  - {kind: crash_at_step, target: master-0, at: 4, exit_code: 17, restart: 0}
"""


def _run_chaos_cli(tmp_path, tag):
    from pytorch_operator_tpu.client import cli

    state = tmp_path / f"state-{tag}"
    job = tmp_path / "job.yaml"
    plan = tmp_path / "plan.yaml"
    job.write_text(CHAOS_JOB)
    plan.write_text(CHAOS_PLAN)
    import contextlib
    import io

    out = io.StringIO()
    with contextlib.redirect_stdout(out):
        rc = cli.main(
            [
                "--state-dir", str(state),
                "chaos", str(job),
                "--plan", str(plan),
                "--timeout", "60",
            ]
        )
    text = out.getvalue()
    summary = [
        line for line in text.splitlines() if line.startswith("chaos ")
    ]
    return rc, text, summary, state


def test_chaos_scenario_end_to_end_and_deterministic(tmp_path):
    """The acceptance scenario: worker crash at step N + one failed
    checkpoint write + one torn checkpoint write + a rendezvous stall,
    replayed via ``tpujob chaos``. The job must complete with an
    exactly-once final status, restore from the last verified-good
    checkpoint, and reproduce the identical event sequence twice."""
    rc1, text1, summary1, state1 = _run_chaos_cli(tmp_path, "a")
    rc2, _, summary2, _ = _run_chaos_cli(tmp_path, "b")
    assert rc1 == 0 and rc2 == 0
    # Determinism: same plan + seed -> byte-identical replay summary.
    assert summary1 == summary2
    seq_line = summary1[0]
    assert seq_line.startswith("chaos events: ")
    seq = seq_line[len("chaos events: "):].split(" -> ")
    # Exactly-once final status; exactly one restart cycle.
    assert seq.count("Normal:TPUJobSucceeded") == 1
    assert seq.count("Warning:TPUJobRestarting") == 1
    assert summary1[1] == "chaos final: Succeeded restarts=1"
    # The failure story is on the event surface, in causal order:
    # injected stall -> crash/restart -> corrupt step skipped -> done.
    assert "Warning:FaultInjected" in seq
    assert seq.index("Warning:TPUJobRestarting") < seq.index(
        "Warning:CheckpointCorrupt"
    ) < seq.index("Normal:TPUJobSucceeded")
    # Restore fell back to the last verified-good step (2: write 3 was
    # torn), and the resumed life completed all 6 steps.
    log = next((state1 / "logs").glob("*master-0.log")).read_text()
    assert "restored step 2" in log
    assert "completed 6 steps (resumed from 2)" in log
    # The torn step was re-written good by the resumed life: every step
    # verifies now.
    from pytorch_operator_tpu.checkpoint import integrity

    ckpt = state1 / "checkpoints" / "default_chaos-e2e"
    assert integrity.list_steps(ckpt) == [1, 2, 3, 4, 5, 6]
    assert integrity.latest_verified_step(ckpt) == 6


@pytest.mark.slow
@pytest.mark.parametrize("crash_step", [1, 3, 6])
@pytest.mark.parametrize("stall_s", [0.0, 0.2])
def test_crash_matrix_sweep(tmp_path, crash_step, stall_s):
    """The long sweep: a crash at every interesting step offset, with
    and without a rendezvous stall — every cell must recover to a
    completed job with exactly one restart."""
    from pytorch_operator_tpu.api import load_job

    job_file = tmp_path / "job.yaml"
    job_file.write_text(CHAOS_JOB)
    plan = FaultPlan(
        seed=7,
        faults=[
            Fault(kind="crash_at_step", target="master-0", at=crash_step,
                  exit_code=21, restart=0),
        ]
        + (
            [Fault(kind="stall_rendezvous", target="master-0",
                   seconds=stall_s, restart=0)]
            if stall_s
            else []
        ),
    )
    faults.arm(plan)
    sup = Supervisor(state_dir=tmp_path / "state")
    try:
        key = sup.submit(load_job(job_file))
        while True:
            sup._inject_pass_faults()
            sup.reconciler.sync(key)
            job = sup.get(key)
            if job.is_finished():
                break
            time.sleep(0.05)
    finally:
        sup.shutdown()
    assert job.is_succeeded()
    assert job.status.restart_count == 1
    log = next((tmp_path / "state" / "logs").glob("*master-0.log")).read_text()
    assert "completed 6 steps" in log
