"""Live-telemetry plumbing (controller/progress.py): tail-reads of the
per-replica status JSONL that workload heartbeats append to."""

from __future__ import annotations

import json

from pytorch_operator_tpu.controller.progress import (
    TAIL_BYTES,
    format_progress,
    read_latest_progress,
)


def _write(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def test_newest_progress_across_replicas_wins(tmp_path):
    _write(
        tmp_path / "master-0.jsonl",
        [
            {"event": "first_step", "ts": 1.0, "step": 0},
            {"event": "progress", "ts": 5.0, "step": 10, "steps_per_sec": 2.0},
        ],
    )
    _write(
        tmp_path / "worker-0.jsonl",
        [{"event": "progress", "ts": 7.0, "step": 14, "steps_per_sec": 2.5}],
    )
    rec = read_latest_progress(tmp_path)
    assert rec["step"] == 14 and rec["replica"] == "worker-0"


def test_missing_dir_and_no_progress_records(tmp_path):
    assert read_latest_progress(tmp_path / "nope") is None
    assert read_latest_progress(None) is None
    _write(tmp_path / "master-0.jsonl", [{"event": "metrics", "ts": 1.0}])
    assert read_latest_progress(tmp_path) is None


def test_torn_and_foreign_lines_skipped(tmp_path):
    p = tmp_path / "master-0.jsonl"
    p.write_text(
        json.dumps({"event": "progress", "ts": 3.0, "step": 6}) + "\n"
        + "{torn json...\n"
        + "42\n"
    )
    rec = read_latest_progress(tmp_path)
    assert rec["step"] == 6


def test_malformed_numeric_fields_rejected_per_record(tmp_path):
    """A foreign writer's record with a non-numeric field must not crash
    describe or poison the daemon's gauge pass — the reader skips THE
    RECORD and falls back to the previous valid one, and every field in
    the result is already a float."""
    p = tmp_path / "master-0.jsonl"
    p.write_text(
        json.dumps({"event": "progress", "ts": 3.0, "step": 6,
                    "steps_per_sec": 2.0}) + "\n"
        + json.dumps({"event": "progress", "ts": 9.0, "step": "resuming",
                      "throughput": ["not", "a", "number"]}) + "\n"
    )
    rec = read_latest_progress(tmp_path)
    assert rec["step"] == 6.0
    assert isinstance(rec["steps_per_sec"], float)


def test_tail_read_finds_newest_in_long_file(tmp_path):
    """A long-trained job's file exceeds the tail window; the newest
    record (at the end) must still be found — and the bounded read must
    not degrade into a whole-file scan."""
    records = [
        {"event": "progress", "ts": float(i), "step": i} for i in range(5000)
    ]
    p = tmp_path / "master-0.jsonl"
    _write(p, records)
    assert p.stat().st_size > 4 * TAIL_BYTES  # precondition: truly long
    rec = read_latest_progress(tmp_path)
    assert rec["step"] == 4999


def test_format_progress_renders_fields():
    lines = format_progress(
        {
            "ts": 90.0,
            "step": 120,
            "loss": 1.23456,
            "steps_per_sec": 3.5,
            "throughput": 448.0,
            "unit": "images/sec/chip",
            "replica": "master-0",
        },
        now=100.0,
    )
    text = "\n".join(lines)
    assert "Step:        120" in text
    assert "Loss:        1.2346" in text
    assert "Steps/sec:   3.50" in text
    assert "448.0 images/sec/chip" in text
    assert "10s ago by master-0" in text
