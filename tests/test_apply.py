"""kubectl-apply semantics (Supervisor.apply / tpujob apply): create if
absent, in-place spec update if active (gang restart only when the world
shape changed), fresh incarnation if finished.
"""

from __future__ import annotations

import copy

from pytorch_operator_tpu.api.defaults import ELASTIC_TARGET_ANNOTATION
from pytorch_operator_tpu.api.types import (
    ElasticPolicy,
    ReplicaPhase,
    ReplicaType,
)
from pytorch_operator_tpu.controller.runner import FakeRunner, replica_name
from pytorch_operator_tpu.controller.supervisor import Supervisor
from tests.testutil import new_job


def make_sup(**kw):
    return Supervisor(state_dir=None, runner=FakeRunner(), persist=False, **kw)


def finish_master(sup, key):
    sup.runner.set_phase(
        replica_name(key, ReplicaType.MASTER, 0), ReplicaPhase.SUCCEEDED, exit_code=0
    )


class TestApply:
    def test_apply_creates_when_absent(self):
        sup = make_sup()
        key = sup.apply(new_job(name="a", workers=1))
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 2

    def test_run_policy_update_does_not_restart_world(self):
        sup = make_sup()
        key = sup.apply(new_job(name="a", workers=1))
        sup.sync_once()
        sup.runner.set_all_running(key)
        updated = new_job(name="a", workers=1)
        updated.spec.run_policy.ttl_seconds_after_finished = 123
        sup.apply(updated)
        sup.sync_once()
        j = sup.get(key)
        assert j.spec.run_policy.ttl_seconds_after_finished == 123
        assert j.status.restart_count == 0  # world untouched
        pids = sup.runner.list_for_job(key)
        assert len(pids) == 2

    def test_world_shape_change_restarts_gang(self):
        sup = make_sup()
        key = sup.apply(new_job(name="a", workers=1))
        sup.sync_once()
        sup.runner.set_all_running(key)
        updated = new_job(name="a", workers=3)  # world shape changed
        sup.apply(updated)
        j = sup.get(key)
        assert j.status.restart_count == 1
        assert any(e.reason == "TPUJobUpdated" for e in sup.events.for_job(key))
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 4

    def test_apply_to_finished_job_starts_fresh_incarnation(self):
        sup = make_sup()
        key = sup.apply(new_job(name="a", workers=0))
        sup.sync_once()
        sup.runner.set_all_running(key)
        finish_master(sup, key)
        sup.sync_once()
        assert sup.get(key).is_succeeded()
        key2 = sup.apply(new_job(name="a", workers=0))
        assert key2 == key
        j = sup.get(key)
        assert not j.is_finished()  # fresh status
        sup.sync_once()
        assert len(sup.runner.list_for_job(key)) == 1

    def test_apply_repins_elastic_target(self):
        sup = make_sup()
        job = new_job(
            name="el", workers=3,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=4, max_restarts=8),
        )
        key = sup.apply(job)
        sup.sync_once()
        sup.runner.set_all_running(key)
        updated = new_job(
            name="el", workers=2,
            elastic=ElasticPolicy(min_replicas=1, max_replicas=4, max_restarts=8),
        )
        sup.apply(updated)
        j = sup.get(key)
        assert j.metadata.annotations[ELASTIC_TARGET_ANNOTATION] == "2"

    def test_apply_explicit_port_clears_auto_port(self):
        """Pinning a port over a previously auto-port job must stick: the
        stale auto-port annotation would make the reconciler re-probe a
        random port at relaunch."""
        from pytorch_operator_tpu.api.defaults import AUTO_PORT_ANNOTATION

        sup = make_sup()
        key = sup.apply(new_job(name="p", workers=0))  # auto-port
        sup.sync_once()
        sup.runner.set_all_running(key)
        # defaulted=False: a real user YAML with an explicit port never
        # carries the auto-port annotation.
        updated = new_job(name="p", workers=0, defaulted=False)
        updated.spec.port = 29501  # explicit pin
        sup.apply(updated)
        j = sup.get(key)
        assert j.spec.port == 29501
        assert AUTO_PORT_ANNOTATION not in j.metadata.annotations
        sup.sync_once()  # relaunched world must use the pinned port
        env = sup.runner.envs[replica_name(key, ReplicaType.MASTER, 0)]
        assert env["MASTER_PORT"] == "29501"
        assert ":29501" in env["TPUJOB_COORDINATOR_ADDRESS"]

    def test_apply_marker_cross_process(self, tmp_path):
        sup = Supervisor(state_dir=tmp_path, runner=FakeRunner(), persist=True)
        key = sup.apply(new_job(name="m", workers=0))
        sup.sync_once()
        updated = new_job(name="m", workers=0)
        updated.spec.run_policy.backoff_limit = 9
        # CLI process leaves the marker; the daemon claims it.
        sup.store.mark_apply(key, updated.to_dict())
        sup.process_apply_markers()
        assert sup.get(key).spec.run_policy.backoff_limit == 9

    def test_invalid_apply_rejected_via_marker(self, tmp_path):
        sup = Supervisor(state_dir=tmp_path, runner=FakeRunner(), persist=True)
        key = sup.apply(new_job(name="m", workers=0))
        bad = new_job(name="m", workers=0, defaulted=False).to_dict()
        del bad["spec"]["replica_specs"]["Master"]  # no Master → invalid
        sup.store.mark_apply(key, bad)
        sup.process_apply_markers()
        assert any(
            e.reason == "TPUJobApplyRejected" for e in sup.events.for_job(key)
        )
        # Original spec untouched.
        assert ReplicaType.MASTER in sup.get(key).spec.replica_specs
