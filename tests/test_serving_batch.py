"""Per-row decode offsets + chunked prefill (the serving-batch
contracts — VERDICT r4 Missing #4).

Round 4's decode stack required batch-uniform positions (cache write
offset read row 0) and start-0 prefill — fine for benchmarks, fatal for
a real request mix where every row of the serving batch is a DIFFERENT
request at a different depth. These tests pin the two generalizations:

- ``decode_per_row=True``: a mixed-depth batch decodes every row at its
  own position, numerically equal to generating each row alone.
- ``prefill_mode="cache"``: a prompt prefilled in chunks (each chunk
  attends against the already-filled cache prefix) equals the one-shot
  prefill, token for token.
"""

from __future__ import annotations

import dataclasses

import numpy as np

import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import llama as llama_lib
from pytorch_operator_tpu.models.llama import decode_forward, init_decode_cache


def _params_and_model(max_decode_len=32, **over):
    import jax
    import flax.linen as nn

    cfg = llama_lib.llama_tiny(
        decode=True, max_decode_len=max_decode_len, **over
    )
    train_model = llama_lib.Llama(dataclasses.replace(
        cfg, decode=False, decode_per_row=False, prefill_mode="self"
    ))
    params = nn.meta.unbox(
        train_model.init(jax.random.key(0), np.zeros((1, 8), np.int32))[
            "params"
        ]
    )
    return cfg, llama_lib.Llama(cfg), params


def _greedy_steps(model, params, cache, last_tok, pos, n):
    """n greedy decode steps through decode_forward at per-row positions
    ``pos`` [B]; returns (tokens [B, n], cache)."""
    import jax.numpy as jnp

    toks = []
    for _ in range(n):
        logits, cache = decode_forward(
            model, params, cache, last_tok[:, None], pos[:, None],
            return_hidden=False,
        )
        last_tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        toks.append(last_tok)
        pos = pos + 1
    return jnp.stack(toks, axis=1), cache


# The parity classes compile many distinct tiny programs (~3 min on this
# one-core host) — fast-lane excluded; TestDebugChecks below stays fast.
@pytest.mark.slow
class TestPerRowDecode:
    def test_mixed_depth_batch_matches_row_by_row(self):
        """The serving-batch property: two requests at DIFFERENT depths
        decode together in one per-row batch, each row numerically equal
        to generating it alone through the uniform path."""
        import jax.numpy as jnp

        L, new = 32, 6
        cfg, _, params = _params_and_model(L)
        uni_model = llama_lib.Llama(cfg)  # batch-uniform (B=1 rows)
        pr_model = llama_lib.Llama(
            dataclasses.replace(cfg, decode_per_row=True)
        )

        rng = np.random.default_rng(0)
        prompts = [
            jnp.asarray(rng.integers(0, cfg.vocab_size, (1, p)), jnp.int32)
            for p in (5, 9)  # different prompt lengths
        ]

        # Reference: each row alone (B=1, uniform contract).
        want, row_caches = [], []
        for prompt in prompts:
            cache = init_decode_cache(cfg, 1)
            logits, cache = decode_forward(
                uni_model, params, cache, prompt, return_hidden=False
            )
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks, cache = _greedy_steps(
                uni_model, params, cache, tok,
                jnp.full((1,), prompt.shape[1], jnp.int32), new - 1,
            )
            want.append(np.concatenate([np.asarray(tok)[:, None],
                                        np.asarray(toks)], axis=1))
            row_caches.append(cache)

        # Serving batch: stitch the per-row caches into one B=2 batch
        # (exactly what the engine's slot assembly does) and decode both
        # rows together at per-row positions.
        import jax

        batch_cache = jax.tree.map(
            lambda *xs: jnp.concatenate(xs, axis=0), *row_caches
        )
        first = jnp.concatenate(
            [jnp.asarray(w[:, :1]) for w in want], axis=0
        )  # each row's first generated token, shape [2, 1]
        pos = jnp.asarray([5, 9], jnp.int32)  # per-row depths
        got, _ = _greedy_steps(
            pr_model, params, batch_cache, first[:, 0], pos, new - 1
        )
        got = np.concatenate([np.asarray(first), np.asarray(got)], axis=1)
        np.testing.assert_array_equal(
            got, np.concatenate(want, axis=0)
        )

    def test_uniform_batch_identical_in_both_modes(self):
        """On a uniform batch the per-row path must be numerically
        identical to the uniform path (same math, scatter vs slice
        write)."""
        import jax.numpy as jnp

        cfg, uni_model, params = _params_and_model(24)
        pr_model = llama_lib.Llama(
            dataclasses.replace(cfg, decode_per_row=True)
        )
        prompt = jnp.asarray(
            np.random.default_rng(1).integers(0, cfg.vocab_size, (3, 8)),
            jnp.int32,
        )
        outs = []
        for model in (uni_model, pr_model):
            cache = init_decode_cache(cfg, 3)
            logits, cache = decode_forward(
                model, params, cache, prompt, return_hidden=False
            )
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks, _ = _greedy_steps(
                model, params, cache, tok,
                jnp.full((3,), 8, jnp.int32), 5,
            )
            outs.append(np.asarray(toks))
        np.testing.assert_array_equal(outs[0], outs[1])

    def test_per_row_composes_with_int8_kv(self):
        """The serving stack quantizes the KV cache; per-row writes must
        quantize/scale per row exactly as the uniform path does."""
        import jax.numpy as jnp

        cfg, uni_model, params = _params_and_model(24, kv_quantize="int8")
        pr_model = llama_lib.Llama(
            dataclasses.replace(cfg, decode_per_row=True)
        )
        prompt = jnp.asarray(
            np.random.default_rng(2).integers(0, cfg.vocab_size, (2, 6)),
            jnp.int32,
        )
        outs = []
        for model in (uni_model, pr_model):
            cache = init_decode_cache(cfg, 2)
            logits, cache = decode_forward(
                model, params, cache, prompt, return_hidden=False
            )
            tok = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            toks, _ = _greedy_steps(
                model, params, cache, tok, jnp.full((2,), 6, jnp.int32), 4
            )
            outs.append(np.asarray(toks))
        np.testing.assert_array_equal(outs[0], outs[1])


@pytest.mark.slow
class TestChunkedPrefill:
    def _one_shot(self, cfg, model, params, prompt):
        import jax.numpy as jnp

        cache = init_decode_cache(cfg, prompt.shape[0])
        logits, cache = decode_forward(
            model, params, cache, prompt, return_hidden=False
        )
        return jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32), cache

    def _chunked(self, cfg, params, prompt, sizes):
        """Prefill ``prompt`` in chunks of the given sizes through the
        prefill_mode='cache' model; returns (next_token, cache)."""
        import jax.numpy as jnp

        model = llama_lib.Llama(
            dataclasses.replace(cfg, prefill_mode="cache")
        )
        B = prompt.shape[0]
        cache = init_decode_cache(cfg, B)
        start = 0
        for size in sizes:
            chunk = prompt[:, start : start + size]
            positions = jnp.broadcast_to(
                jnp.arange(start, start + size, dtype=jnp.int32), (B, size)
            )
            logits, cache = decode_forward(
                model, params, cache, chunk, positions, return_hidden=False
            )
            start += size
        assert start == prompt.shape[1], "sizes must cover the prompt"
        return jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32), cache

    def test_chunked_equals_one_shot(self):
        """The chunked-prefill property: any chunking of the prompt
        (equal chunks, ragged chunks, single-token chunks) produces the
        same cache and the same next token as the one-shot prefill."""
        import jax

        cfg, model, params = _params_and_model(32)
        prompt = np.random.default_rng(3).integers(
            0, cfg.vocab_size, (2, 12)
        ).astype(np.int32)
        want_tok, want_cache = self._one_shot(cfg, model, params, prompt)
        for sizes in ([4, 4, 4], [5, 7], [12], [1] * 12):
            got_tok, got_cache = self._chunked(cfg, params, prompt, sizes)
            np.testing.assert_array_equal(
                np.asarray(got_tok), np.asarray(want_tok),
                err_msg=f"chunking {sizes}",
            )
            # The caches must agree everywhere (unwritten slots are
            # zeros in both).
            for w, g in zip(
                jax.tree.leaves(want_cache), jax.tree.leaves(got_cache)
            ):
                np.testing.assert_allclose(
                    np.asarray(g), np.asarray(w), rtol=2e-5, atol=2e-5
                )

    def test_chunked_rollout_matches_one_shot_rollout(self):
        """End to end: greedy decode after a chunked prefill equals the
        rollout after one-shot prefill."""
        import jax.numpy as jnp

        cfg, model, params = _params_and_model(32)
        prompt = np.random.default_rng(4).integers(
            0, cfg.vocab_size, (2, 10)
        ).astype(np.int32)
        tok_a, cache_a = self._one_shot(cfg, model, params, prompt)
        toks_a, _ = _greedy_steps(
            model, params, cache_a, tok_a, jnp.full((2,), 10, jnp.int32), 6
        )
        tok_b, cache_b = self._chunked(cfg, params, prompt, [3, 3, 4])
        toks_b, _ = _greedy_steps(
            model, params, cache_b, tok_b, jnp.full((2,), 10, jnp.int32), 6
        )
        np.testing.assert_array_equal(np.asarray(tok_a), np.asarray(tok_b))
        np.testing.assert_array_equal(np.asarray(toks_a), np.asarray(toks_b))

    def test_chunked_composes_with_int8_kv(self):
        """Chunked prefill under int8 KV: every cache-mode chunking is
        bit-identical to every other (cache mode ALWAYS reads the
        quantized cache, so chunk boundaries can't change what any
        token sees). Against the one-shot SELF-mode prefill the caches
        agree only to quantization tolerance: self-attention reads the
        exact k/v while cache mode reads their int8 round trip, and
        that ulp difference propagates through layer>=1 hidden states
        into the later layers' cache writes."""
        import jax

        cfg, model, params = _params_and_model(32, kv_quantize="int8")
        prompt = np.random.default_rng(5).integers(
            0, cfg.vocab_size, (2, 8)
        ).astype(np.int32)
        _, cache_a = self._one_shot(cfg, model, params, prompt)
        tok_b, cache_b = self._chunked(cfg, params, prompt, [4, 4])
        tok_c, cache_c = self._chunked(cfg, params, prompt, [2, 6])
        tok_d, cache_d = self._chunked(cfg, params, prompt, [8])
        np.testing.assert_array_equal(np.asarray(tok_b), np.asarray(tok_c))
        np.testing.assert_array_equal(np.asarray(tok_b), np.asarray(tok_d))
        for w, g in zip(
            jax.tree.leaves(cache_b), jax.tree.leaves(cache_c)
        ):
            np.testing.assert_array_equal(np.asarray(g), np.asarray(w))

        def dequant(slab):
            return {
                "k": np.asarray(slab["cached_key"], np.float32)
                * np.asarray(slab["key_scale"]),
                "v": np.asarray(slab["cached_value"], np.float32)
                * np.asarray(slab["value_scale"]),
            }

        for layer in cache_a:
            a = dequant(cache_a[layer]["attn"])
            b = dequant(cache_b[layer]["attn"])
            for key in ("k", "v"):
                np.testing.assert_allclose(
                    b[key], a[key], rtol=0.05, atol=0.02
                )


class TestDebugChecks:
    def test_per_row_model_accepts_ragged_positions(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("TPUJOB_DEBUG_CHECKS", "1")
        cfg, _, params = _params_and_model(16)
        pr_model = llama_lib.Llama(
            dataclasses.replace(cfg, decode_per_row=True)
        )
        cache = init_decode_cache(cfg, 2)
        tok = jnp.zeros((2, 1), jnp.int32)
        pos = jnp.asarray([[3], [7]], jnp.int32)  # ragged: fine per-row
        out, _ = decode_forward(pr_model, params, cache, tok, pos)
        jax.block_until_ready(out)

    def test_overflow_positions_rejected(self, monkeypatch):
        import jax
        import jax.numpy as jnp
        import pytest

        monkeypatch.setenv("TPUJOB_DEBUG_CHECKS", "1")
        cfg, _, params = _params_and_model(16)
        pr_model = llama_lib.Llama(
            dataclasses.replace(cfg, decode_per_row=True)
        )
        cache = init_decode_cache(cfg, 2)
        tok = jnp.zeros((2, 1), jnp.int32)
        pos = jnp.asarray([[3], [16]], jnp.int32)  # row 1 past the cache
        with pytest.raises(Exception, match="max_decode_len"):
            out, _ = decode_forward(pr_model, params, cache, tok, pos)
            jax.block_until_ready(out)

    def test_self_mode_still_rejects_nonzero_prefill_start(
        self, monkeypatch
    ):
        import jax
        import jax.numpy as jnp
        import pytest

        monkeypatch.setenv("TPUJOB_DEBUG_CHECKS", "1")
        cfg, model, params = _params_and_model(16)
        cache = init_decode_cache(cfg, 1)
        toks = jnp.zeros((1, 4), jnp.int32)
        pos = jnp.arange(2, 6, dtype=jnp.int32)[None, :]
        with pytest.raises(Exception, match="prefill"):
            out, _ = decode_forward(model, params, cache, toks, pos)
            jax.block_until_ready(out)

    def test_cache_mode_accepts_nonzero_prefill_start(self, monkeypatch):
        import jax
        import jax.numpy as jnp

        monkeypatch.setenv("TPUJOB_DEBUG_CHECKS", "1")
        cfg, _, params = _params_and_model(16)
        model = llama_lib.Llama(
            dataclasses.replace(cfg, prefill_mode="cache")
        )
        cache = init_decode_cache(cfg, 1)
        toks = jnp.zeros((1, 4), jnp.int32)
        pos = jnp.arange(2, 6, dtype=jnp.int32)[None, :]
        out, _ = decode_forward(model, params, cache, toks, pos)
        jax.block_until_ready(out)
