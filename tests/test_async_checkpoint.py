"""Async verified checkpointing: commit protocol, barriers, and the
crash/disk-full chaos scenarios.

The old ``save(block=False)`` skipped the checksum sidecar — async-saved
steps were unverifiable forever. The async writer
(checkpoint/async_writer.py) closes that hole: snapshot at save-call,
single-threaded commits in submission order, sidecar AT COMMIT, inflight
fencing for crash consistency, and wait()/close() barriers everything
drains through. These tests pin each leg, from jax-free writer units
through orbax-manager integration to real-subprocess chaos casualties
(kill mid-commit; disk full during save).
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

import pytest

from pytorch_operator_tpu import faults
from pytorch_operator_tpu.checkpoint import integrity
from pytorch_operator_tpu.checkpoint.async_writer import (
    AsyncCheckpointWriter,
    snapshot_to_host,
)
from pytorch_operator_tpu.faults import Fault, FaultPlan

pytestmark = pytest.mark.chaos


# ---- writer units (jax-free) ----


class TestAsyncWriter:
    def _json_commit(self, root: Path, delay: float = 0.0, order=None):
        def commit(step, payload, fault):
            if fault == "fail":
                raise OSError("injected")
            d = root / str(step)
            d.mkdir(parents=True, exist_ok=True)
            (d / "state.json").write_text(json.dumps({"step": step}))
            if delay:
                time.sleep(delay)
            integrity.write_sidecar(root, step)
            if order is not None:
                order.append(step)

        return commit

    def test_commits_serialize_in_submission_order(self, tmp_path):
        """Save-while-save-in-flight: one commit thread, FIFO — commits
        never interleave or reorder."""
        order = []
        w = AsyncCheckpointWriter(
            self._json_commit(tmp_path, delay=0.02, order=order),
            root=tmp_path,
        )
        for s in range(1, 6):
            w.submit(s, None)
        w.close()
        assert order == [1, 2, 3, 4, 5]
        assert w.committed == [1, 2, 3, 4, 5]
        assert w.last_committed_step() == 5

    def test_wait_drains_all_pending(self, tmp_path):
        w = AsyncCheckpointWriter(
            self._json_commit(tmp_path, delay=0.05), root=tmp_path
        )
        w.submit(1, None)
        w.submit(2, None)
        assert w.pending()
        w.wait()
        assert not w.pending()
        assert integrity.verify_step(tmp_path, 2) is True
        w.close()

    def test_close_refuses_further_submits(self, tmp_path):
        w = AsyncCheckpointWriter(self._json_commit(tmp_path), root=tmp_path)
        w.submit(1, None)
        w.close()
        with pytest.raises(RuntimeError):
            w.submit(2, None)

    def test_failed_commit_recorded_and_later_saves_proceed(self, tmp_path):
        errs = []
        w = AsyncCheckpointWriter(
            self._json_commit(tmp_path),
            root=tmp_path,
            on_error=lambda s, e: errs.append(s),
        )
        w.submit(1, None)
        w.submit(2, None, fault="fail")  # commit raises
        w.submit(3, None)
        w.close()
        assert [s for s, _ in w.errors] == [2] and errs == [2]
        assert w.committed == [1, 3]
        # The failed step's inflight fence was cleared (no phantom fence
        # condemning a step that was never written).
        assert not integrity.inflight_path(tmp_path, 2).exists()
        assert integrity.latest_verified_step(tmp_path) == 3

    def test_inflight_fence_lifecycle(self, tmp_path):
        """Fence on disk from submit until the sidecar commits; a step
        still fenced verifies as uncommitted (False), never unknown."""
        gate = threading.Event()

        def commit(step, payload, fault):
            d = tmp_path / str(step)
            d.mkdir(parents=True, exist_ok=True)
            (d / "state.json").write_text("{}")
            gate.wait(5)  # hold mid-commit: state written, no sidecar
            integrity.write_sidecar(tmp_path, step)

        w = AsyncCheckpointWriter(commit, root=tmp_path)
        w.submit(7, None)
        for _ in range(100):
            if (tmp_path / "7").exists():
                break
            time.sleep(0.01)
        assert integrity.inflight_path(tmp_path, 7).exists()
        assert integrity.verify_step(tmp_path, 7) is False  # fenced
        gate.set()
        w.close()
        assert not integrity.inflight_path(tmp_path, 7).exists()
        assert integrity.verify_step(tmp_path, 7) is True

    def test_backpressure_bounds_pending_snapshots(self, tmp_path):
        """max_pending caps host-resident snapshots: the 3rd submit
        blocks until a commit frees a slot — backpressure, not OOM."""
        release = threading.Event()

        def commit(step, payload, fault):
            release.wait(5)

        w = AsyncCheckpointWriter(commit, root=tmp_path, max_pending=2)
        w.submit(1, None)
        w.submit(2, None)
        t0 = time.monotonic()
        blocked = threading.Event()

        def third():
            blocked.set()
            w.submit(3, None)

        t = threading.Thread(target=third, daemon=True)
        t.start()
        blocked.wait(5)
        time.sleep(0.05)
        assert t.is_alive()  # still blocked on the slot
        release.set()
        t.join(5)
        assert not t.is_alive()
        assert time.monotonic() - t0 >= 0.05
        w.close()

    def test_snapshot_to_host_owns_its_bytes(self):
        import numpy as np

        src = {"w": np.ones((4, 4), np.float32), "n": 3}
        snap = snapshot_to_host(src)
        src["w"][:] = 7.0  # donation/in-place update analog
        assert (snap["w"] == 1.0).all()
        assert snap["n"] == 3

    # ---- staged snapshot stage (PR 8) ----

    def test_staged_submits_keep_submission_order_mixed_with_eager(
        self, tmp_path
    ):
        """Staged and eager submits flow through the same
        snapshot→commit chain: commits land in exact submission order
        no matter which flavor each save used."""
        order = []
        w = AsyncCheckpointWriter(
            self._json_commit(tmp_path, delay=0.01, order=order),
            root=tmp_path,
        )
        w.submit(1, None)
        w.submit_staged(2, lambda: None)
        w.submit(3, None)
        w.submit_staged(4, lambda: None)
        w.close()
        assert order == [1, 2, 3, 4]
        assert w.committed == [1, 2, 3, 4]

    def test_staged_submit_returns_before_snapshot_runs(self, tmp_path):
        """The tentpole contract: submit_staged pays only the fence
        write — the gather happens later, on the snapshot thread."""
        started = threading.Event()
        release = threading.Event()

        def slow_snapshot():
            started.set()
            release.wait(5)
            return {"s": 7}

        w = AsyncCheckpointWriter(self._json_commit(tmp_path), root=tmp_path)
        w.submit_staged(7, slow_snapshot)
        # Returned while the snapshot is still running (or not started);
        # the fence is already on disk.
        assert integrity.inflight_path(tmp_path, 7).exists()
        assert started.wait(5)
        assert w.stats()["snapshot_depth"] == 1
        release.set()
        w.close()
        assert integrity.verify_step(tmp_path, 7) is True
        assert w.stats()["snapshot_depth"] == 0

    def test_failed_snapshot_recorded_fence_cleared_later_saves_proceed(
        self, tmp_path
    ):
        """A gather that raises (e.g. donated-buffer misuse) must be a
        recorded failure like a failed commit — never a dead pipeline."""
        errs = []

        def boom():
            raise RuntimeError("gather exploded")

        w = AsyncCheckpointWriter(
            self._json_commit(tmp_path),
            root=tmp_path,
            on_error=lambda s, e: errs.append(s),
        )
        w.submit_staged(1, lambda: None)
        w.submit_staged(2, boom)
        w.submit_staged(3, lambda: None)
        w.close()
        assert [s for s, _ in w.errors] == [2] and errs == [2]
        assert w.committed == [1, 3]
        assert not integrity.inflight_path(tmp_path, 2).exists()
        assert integrity.latest_verified_step(tmp_path) == 3

    def test_wait_returns_false_on_timeout_true_when_drained(self, tmp_path):
        """Satellite: the barrier must SAY when it gave up — a silent
        return with commits pending let exit paths proceed past
        undrained saves."""
        release = threading.Event()

        def commit(step, payload, fault):
            release.wait(5)
            integrity.write_sidecar(tmp_path, step)

        (tmp_path / "1").mkdir()
        w = AsyncCheckpointWriter(commit, root=tmp_path)
        w.submit(1, None)
        assert w.wait(0.05) is False  # timed out, commit still pending
        release.set()
        assert w.wait(5.0) is True
        w.close()

    def test_close_timeout_warns_and_returns_false(self, tmp_path, capsys):
        release = threading.Event()

        def commit(step, payload, fault):
            release.wait(10)

        w = AsyncCheckpointWriter(commit, root=tmp_path)
        w.submit(1, None)
        assert w.close(timeout=0.05) is False
        out = capsys.readouterr().out
        assert "drain timed out" in out
        release.set()

    def test_stage_mutable_leaves_copies_numpy_keeps_rest(self):
        import numpy as np

        from pytorch_operator_tpu.checkpoint.async_writer import (
            stage_mutable_leaves,
        )

        src = {"w": np.ones((4,), np.float32), "n": 3, "s": "tag"}
        held = stage_mutable_leaves(src)
        src["w"][:] = -1.0  # in-place mutation after submit
        assert (held["w"] == 1.0).all()  # the copy is isolated
        assert held["n"] == 3 and held["s"] == "tag"


# ---- orbax manager integration ----


def _state(v: float):
    import tests.jaxenv  # noqa: F401
    import jax.numpy as jnp

    return {"w": jnp.full((64, 32), v), "step": jnp.asarray(int(v))}


class TestManagerAsync:
    def test_async_steps_verify_and_restore(self, ckpt_mgr_dir):
        from pytorch_operator_tpu.checkpoint import CheckpointManager

        with CheckpointManager(ckpt_mgr_dir, max_to_keep=10) as mgr:
            mgr.save(1, _state(1.0), block=False)
            mgr.save(2, _state(2.0), block=False)
            # The read side drains: no sleep needed, the barrier is the API.
            assert mgr.latest_verified_step() == 2
            assert integrity.verify_step(ckpt_mgr_dir, 1) is True
            step, st = mgr.restore_or_none(_state(0.0))
        import numpy as np

        assert step == 2
        np.testing.assert_allclose(np.asarray(st["w"]), 2.0)

    def test_snapshot_isolates_from_inplace_update(self, ckpt_mgr_dir):
        """The save-call snapshot means mutating (donating) the state
        right after save(block=False) cannot tear the commit."""
        import numpy as np

        from pytorch_operator_tpu.checkpoint import CheckpointManager

        state = {"w": np.full((64, 32), 5.0, np.float32)}
        with CheckpointManager(ckpt_mgr_dir) as mgr:
            mgr.save(1, state, block=False)
            state["w"][:] = -1.0  # the next "step" updates in place
            step, st = mgr.restore_or_none({"w": np.zeros((64, 32), np.float32)})
        assert step == 1
        np.testing.assert_allclose(np.asarray(st["w"]), 5.0)

    def test_staged_steps_verify_and_restore(self, ckpt_mgr_dir):
        """Staged saves are first-class VERIFIED checkpoints exactly
        like eager async ones — the read side drains through both
        stages."""
        import numpy as np

        from pytorch_operator_tpu.checkpoint import CheckpointManager

        with CheckpointManager(
            ckpt_mgr_dir, max_to_keep=10, staged=True
        ) as mgr:
            mgr.save(1, _state(1.0), block=False)
            mgr.save(2, _state(2.0), block=False)
            assert mgr.latest_verified_step() == 2
            assert integrity.verify_step(ckpt_mgr_dir, 1) is True
            step, st = mgr.restore_or_none(_state(0.0))
        assert step == 2
        np.testing.assert_allclose(np.asarray(st["w"]), 2.0)

    def test_staged_save_isolates_mutable_host_leaves(self, ckpt_mgr_dir):
        """The deferred gather still copies MUTABLE (numpy) leaves at
        submit: in-place updates right after save(block=False) cannot
        tear the staged commit."""
        import numpy as np

        from pytorch_operator_tpu.checkpoint import CheckpointManager

        state = {"w": np.full((64, 32), 5.0, np.float32)}
        with CheckpointManager(ckpt_mgr_dir, staged=True) as mgr:
            mgr.save(1, state, block=False)
            state["w"][:] = -1.0  # the next "step" updates in place
            step, st = mgr.restore_or_none(
                {"w": np.zeros((64, 32), np.float32)}
            )
        assert step == 1
        np.testing.assert_allclose(np.asarray(st["w"]), 5.0)

    def test_per_call_staged_override_wins(self, ckpt_mgr_dir):
        """save(..., staged=) overrides the manager default — the
        donate-path escape hatch."""
        from pytorch_operator_tpu.checkpoint import CheckpointManager

        with CheckpointManager(
            ckpt_mgr_dir, max_to_keep=10, staged=True
        ) as mgr:
            mgr.save(1, _state(1.0), block=False, staged=False)  # eager
            mgr.save(2, _state(2.0), block=False)  # staged default
            assert mgr.latest_verified_step() == 2

    def test_manager_wait_timeout_returns_false_and_warns(
        self, ckpt_mgr_dir, capsys
    ):
        import threading as _threading

        from pytorch_operator_tpu.checkpoint import CheckpointManager

        gate = _threading.Event()
        with CheckpointManager(ckpt_mgr_dir, max_to_keep=10) as mgr:
            mgr.save(1, _state(1.0))  # blocking: builds the writer lazily?
            # Use a staged save whose snapshot blocks to hold the drain.
            mgr._staged = True
            mgr.save(2, _state(2.0), block=False)
            # Block the pipeline with a snapshot that waits on the gate.
            mgr._writer.submit_staged(3, lambda: gate.wait(10) and {})
            assert mgr.wait(0.05) is False
            assert "drain timed out" in capsys.readouterr().out
            gate.set()
            assert mgr.wait(10.0) is True

    def test_torn_fault_fires_inside_async_commit(self, ckpt_mgr_dir):
        """torn_checkpoint_write on an ASYNC save: corrupt bytes under a
        stale sidecar, caught by the verified-good scan — the fault site
        works identically on the background commit thread."""
        from pytorch_operator_tpu.checkpoint import CheckpointManager

        faults.disarm()
        faults.arm(
            FaultPlan(faults=[Fault(kind="torn_checkpoint_write", nth=2)])
        )
        try:
            with CheckpointManager(ckpt_mgr_dir, max_to_keep=10) as mgr:
                mgr.save(1, _state(1.0), block=False)
                mgr.save(2, _state(2.0), block=False)
                assert mgr.latest_verified_step() == 1  # step 2 torn
                step, _ = mgr.restore_or_none(_state(0.0))
                assert step == 1
        finally:
            faults.disarm()

    def test_enospc_blocking_save_raises_and_cleans(self, ckpt_mgr_dir):
        """Disk full is persistent: every retry fails, save() raises, and
        NO partial step survives (a sidecar-less directory would restore
        as a legacy 'unknown' step)."""
        import errno

        from pytorch_operator_tpu.checkpoint import CheckpointManager

        faults.disarm()
        faults.arm(
            FaultPlan(faults=[Fault(kind="enospc_checkpoint_write", nth=2)])
        )
        try:
            with CheckpointManager(ckpt_mgr_dir, max_to_keep=10) as mgr:
                mgr.save(1, _state(1.0))
                with pytest.raises(OSError) as ei:
                    mgr.save(2, _state(2.0))
                assert ei.value.errno == errno.ENOSPC
                assert not (Path(ckpt_mgr_dir) / "2").exists()
                # The loop survives: the NEXT save lands and verifies.
                mgr.save(3, _state(3.0))
                assert mgr.latest_verified_step() == 3
        finally:
            faults.disarm()

    def test_enospc_async_commit_reported_not_raised(
        self, ckpt_mgr_dir, monkeypatch, tmp_path
    ):
        """On the async path a lost save must never kill the step loop:
        the failure is recorded on the writer, reported on the status
        channel, and restore falls back to the last verified step."""
        from pytorch_operator_tpu.checkpoint import CheckpointManager

        status = tmp_path / "status"
        status.mkdir()
        monkeypatch.setenv("TPUJOB_STATUS_DIR", str(status))
        monkeypatch.setenv("TPUJOB_REPLICA_TYPE", "Master")
        monkeypatch.setenv("TPUJOB_REPLICA_INDEX", "0")
        faults.disarm()
        faults.arm(
            FaultPlan(faults=[Fault(kind="enospc_checkpoint_write", nth=2)])
        )
        try:
            with CheckpointManager(ckpt_mgr_dir, max_to_keep=10) as mgr:
                mgr.save(1, _state(1.0), block=False)
                mgr.save(2, _state(2.0), block=False)  # lost to ENOSPC
                mgr.save(3, _state(3.0), block=False)
                mgr.wait()
                assert [s for s, _ in mgr._writer.errors] == [2]
                assert mgr.all_steps() == [1, 3]
                assert mgr.latest_verified_step() == 3
            recs = [
                json.loads(line)
                for line in (status / "master-0.jsonl").read_text().splitlines()
            ]
            failed = [r for r in recs if r["event"] == "checkpoint_save_failed"]
            assert failed and failed[0]["step"] == 2
        finally:
            faults.disarm()


@pytest.fixture
def ckpt_mgr_dir(tmp_path):
    return tmp_path / "ckpts"


# ---- real-subprocess chaos (exit_with casualties) ----

ASYNC_CRASH_JOB = """\
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: async-crash
spec:
  replica_specs:
    Master:
      replicas: 1
      restart_policy: OnFailure
      template:
        module: pytorch_operator_tpu.workloads.exit_with
        args: ["--steps", "6", "--async-checkpoint", "--commit-time", "0.25"]
  run_policy:
    backoff_limit: 3
"""

KILL_JOB = """\
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: async-kill
spec:
  replica_specs:
    Master:
      replicas: 1
      restart_policy: OnFailure
      template:
        module: pytorch_operator_tpu.workloads.exit_with
        args: ["--steps", "8", "--step-time", "0.05", "--async-checkpoint",
               "--commit-time", "0.3"]
  run_policy:
    backoff_limit: 3
"""

STAGED_KILL_JOB = """\
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: staged-kill
spec:
  replica_specs:
    Master:
      replicas: 1
      restart_policy: OnFailure
      template:
        module: pytorch_operator_tpu.workloads.exit_with
        args: ["--steps", "8", "--step-time", "0.05", "--staged-checkpoint",
               "--snapshot-time", "0.3"]
  run_policy:
    backoff_limit: 3
"""

ENOSPC_JOB = """\
api_version: tpujob.dev/v1
kind: TPUJob
metadata:
  name: enospc
spec:
  replica_specs:
    Master:
      replicas: 1
      restart_policy: OnFailure
      template:
        module: pytorch_operator_tpu.workloads.exit_with
        args: ["--steps", "6", "--step-time", "0.02"]
  run_policy:
    backoff_limit: 3
"""


def _run_job_with_plan(tmp_path, job_yaml: str, plan: FaultPlan):
    """Drive a job to completion under an in-process supervisor with the
    plan armed (the test_crash_matrix_sweep idiom). Returns (job, state
    dir)."""
    from pytorch_operator_tpu.api import load_job
    from pytorch_operator_tpu.controller.supervisor import Supervisor

    job_file = tmp_path / "job.yaml"
    job_file.write_text(job_yaml)
    faults.disarm()
    faults.arm(plan)
    sup = Supervisor(state_dir=tmp_path / "state")
    try:
        key = sup.submit(load_job(job_file))
        deadline = time.time() + 60
        while time.time() < deadline:
            sup._inject_pass_faults()
            sup.reconciler.sync(key)
            job = sup.get(key)
            if job.is_finished():
                break
            time.sleep(0.05)
    finally:
        sup.shutdown()
        faults.disarm()
    return job, tmp_path / "state"


def _master_log(state: Path) -> str:
    return "".join(
        p.read_text() for p in sorted((state / "logs").glob("*master-0.log"))
    )


def test_crash_mid_async_commit_resumes_from_verified_step(tmp_path):
    """Deterministic mid-commit casualty: with commit-time 0.25 the
    writer's backpressure paces the loop so that at the step-5 crash,
    steps 1-2 are committed (sidecars), step 3 is mid-commit (fenced
    inflight) and step 4 is queued (fenced). The restart must skip the
    fenced steps — whatever bytes the crash left — and resume from the
    last SIDECAR-VERIFIED step, 2."""
    plan = FaultPlan(
        seed=11,
        faults=[
            Fault(kind="crash_at_step", target="master-0", at=5,
                  exit_code=23, restart=0)
        ],
    )
    job, state = _run_job_with_plan(tmp_path, ASYNC_CRASH_JOB, plan)
    assert job.is_succeeded()
    assert job.status.restart_count == 1
    log = _master_log(state)
    assert "restored step 2" in log, log
    assert "completed 6 steps (resumed from 2)" in log
    # The resumed life re-ran 3..6 and re-committed them: nothing is
    # fenced or corrupt at the end.
    ckpt = state / "checkpoints" / "default_async-crash"
    assert integrity.latest_verified_step(ckpt) == 6
    assert not list(ckpt.glob("*.inflight"))


def test_kill_replica_mid_async_commit_recovers(tmp_path):
    """The ROADMAP scenario: SIGKILL (kill_replica) lands while async
    commits are in flight. Invariants (kill timing is the supervisor
    pass, not a step index): exactly one restart is spent, the restart
    resumes from a sidecar-verified step, and the finished job's
    checkpoint dir is fully verified with no stale fences."""
    plan = FaultPlan(
        seed=13,
        faults=[Fault(kind="kill_replica", target="master-0", at=3)],
    )
    job, state = _run_job_with_plan(tmp_path, KILL_JOB, plan)
    assert job.is_succeeded()
    assert job.status.restart_count == 1
    log = _master_log(state)
    assert "restored step" in log or "completed 8 steps (resumed from 0)" in log
    ckpt = state / "checkpoints" / "default_async-kill"
    assert integrity.latest_verified_step(ckpt) == 8
    assert not list(ckpt.glob("*.inflight"))
    # The step the second life resumed from was VERIFIED at restore time
    # (never a fenced/uncommitted one): exit_with logs the fallback for
    # every skipped step, and the resume line names the verified target.
    import re

    m = re.search(r"completed 8 steps \(resumed from (\d+)\)", log)
    assert m, log


def test_kill_replica_mid_staged_snapshot_leaves_fenced_not_torn(tmp_path):
    """PR-8 chaos acceptance: SIGKILL lands while saves sit in the
    STAGED pipeline (snapshot-time 0.3 ≫ step-time 0.05, so at any kill
    instant at least one step is fenced with its gather still pending —
    no bytes written at all). Invariants: the kill spends exactly one
    restart, the restart restores from a sidecar-VERIFIED step (a
    fenced step is uncommitted, never 'unknown-accepted'), and the
    finished job's checkpoint dir is fully verified with no stale
    fences left behind."""
    plan = FaultPlan(
        seed=29,
        faults=[Fault(kind="kill_replica", target="master-0", at=3)],
    )
    job, state = _run_job_with_plan(tmp_path, STAGED_KILL_JOB, plan)
    assert job.is_succeeded()
    assert job.status.restart_count == 1
    log = _master_log(state)
    import re

    m = re.search(r"completed 8 steps \(resumed from (\d+)\)", log)
    assert m, log
    ckpt = state / "checkpoints" / "default_staged-kill"
    assert integrity.latest_verified_step(ckpt) == 8
    assert not list(ckpt.glob("*.inflight"))


def test_disk_full_save_fails_loop_survives_restore_falls_back(tmp_path):
    """The ROADMAP disk-full scenario: the step-3 save hits persistent
    ENOSPC — retries exhaust, the step LOOP SURVIVES (training goes on),
    and after a later crash the restart restores from the last verified
    step (2, since step 3's save was lost)."""
    plan = FaultPlan(
        seed=17,
        faults=[
            Fault(kind="enospc_checkpoint_write", target="master-0",
                  nth=3, restart=0),
            Fault(kind="crash_at_step", target="master-0", at=4,
                  exit_code=19, restart=0),
        ],
    )
    job, state = _run_job_with_plan(tmp_path, ENOSPC_JOB, plan)
    assert job.is_succeeded()
    assert job.status.restart_count == 1
    log = _master_log(state)
    # Life 1: the failed save is reported, then step 4 still ran (the
    # crash fault fired there — proof the loop outlived the lost save).
    assert "checkpoint save of step 3 failed after retries" in log, log
    # Life 2: recovery degraded to the last VERIFIED step, not step 3.
    assert "restored step 2" in log
    assert "completed 6 steps (resumed from 2)" in log
    ckpt = state / "checkpoints" / "default_enospc"
    assert integrity.latest_verified_step(ckpt) == 6
