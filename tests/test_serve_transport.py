"""Serve transport tiers: batched framing, shm rings, sharded routers.

The memory-speed serve-plane PR's tier-1 pins, from the framing bytes
up to the live router:

- batched ``.jsonb`` framing is torn-tolerant: a writer killed
  mid-batch loses at most the torn frame — every complete record
  before, between and after is recovered, none twice;
- the syscall budget: enqueueing a burst through ``enqueue_batch``
  costs at most a QUARTER of the per-file path's spool ops, producer
  and consumer side both (the bar the batching exists to clear);
- client waits and idle scans ride the shared adaptive backoff — poll
  counts are pinned, so a regression back to fixed-interval spinning
  fails loudly;
- the shm ring is SPSC-correct through wraparound, full-ring spill,
  corruption (crc), and re-attach (cursors live in the file, so ring
  state survives a peer restart);
- the ring tier NEVER owns exactly-once: a ring peer killed mid-flight
  spills to the file path and the front spool still publishes exactly
  once — including when the dead peer resurrects and answers late;
- sharded routers preserve the same contract: N worker lanes, hash
  partitioning, every response published once, idle scan counts
  bounded by the backoff cap.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from pytorch_operator_tpu.serving import Spool
from pytorch_operator_tpu.serving.router import (
    ServeRouter,
    front_spool_dir,
    replica_spool_dir,
    serve_root_dir,
    shard_of,
)
from pytorch_operator_tpu.serving.shmring import (
    HEADER_BYTES,
    REC_HEADER,
    EngineRingPort,
    EngineTransport,
    RouterRingPort,
    ShmRing,
)
from pytorch_operator_tpu.serving.spool import decode_frames, encode_frames
from pytorch_operator_tpu.workloads import serveplane_bench
from pytorch_operator_tpu.api.types import ReplicaType

pytestmark = pytest.mark.bench_smoke


def _recs(n, tag="r"):
    return [
        {"id": f"{tag}{i:04d}", "prompt_len": 4, "max_new_tokens": 2,
         "submit_time": 1.0 + i}
        for i in range(n)
    ]


# ---- batched framing ----


class TestBatchFraming:
    def test_torn_tail_loses_only_the_torn_frame(self):
        """The crash-mid-write shape: a batch file truncated inside its
        last frame decodes every complete frame and counts one torn."""
        data = encode_frames(_recs(5))
        recs, torn = decode_frames(data[:-3])
        assert [r["id"] for r in recs] == ["r0000", "r0001", "r0002", "r0003"]
        assert torn == 1

    def test_corrupt_middle_frame_is_skipped_not_fatal(self):
        """A bit-flip in frame k must not take frames k+1.. with it —
        the per-line crc localizes the damage."""
        lines = encode_frames(_recs(4)).split(b"\n")
        # Flip one payload byte of the second record (after the crc).
        bad = bytearray(lines[1])
        bad[-2] ^= 0xFF
        lines[1] = bytes(bad)
        recs, torn = decode_frames(b"\n".join(lines))
        assert [r["id"] for r in recs] == ["r0000", "r0002", "r0003"]
        assert torn == 1

    def test_claim_of_torn_batch_recovers_complete_records_once(self, tmp_path):
        """End to end through the spool: truncate an enqueued batch
        file mid-frame; claim() yields every complete record exactly
        once and a re-claim yields nothing."""
        sp = Spool(tmp_path / "spool")
        sp.enqueue_batch(_recs(8))
        (batch,) = list(sp.requests.glob("*.jsonb"))
        data = batch.read_bytes()
        batch.write_bytes(data[: len(data) - 5])  # tear the last frame
        got = sp.claim(16)
        assert [r["id"] for r in got] == [f"r{i:04d}" for i in range(7)]
        assert sp.claim(16) == []
        assert sp.pending_count() == 0

    def test_recovered_batch_dedups_answered_records(self, tmp_path):
        """Engine-restart replay: a re-claimed batch pays the
        per-record response probe, so already-answered records are not
        handed out again."""
        sp = Spool(tmp_path / "spool")
        sp.enqueue_batch(_recs(3))
        got = sp.claim(16)
        assert len(got) == 3
        sp.respond("r0001", {"id": "r0001", "tokens": [1]})
        assert sp.recover_claimed() >= 1
        again = sp.claim(16)
        assert sorted(r["id"] for r in again) == ["r0000", "r0002"]
        # The answered record kept its one response.
        assert sp.read_response("r0001")["tokens"] == [1]


# ---- cross-host spill path: shared-filesystem visibility lag ----


class TestCrossHostSpillLag:
    """The ring's file-spool spill tier over a SHARED filesystem.

    On one host the maildir discipline is airtight: rename is atomic
    and a reader sees either the whole file or nothing. A shared
    filesystem (the cross-host spill path) weakens both halves: a
    rename lands on the writer host but becomes VISIBLE to the reader's
    directory scan only after an attribute-cache window, and a file's
    size can be visible BEFORE its content (the reader gets the final
    length but stale/zero pages for the not-yet-propagated tail).
    The spill contract must hold anyway: every record served exactly
    once, late — never lost, never twice."""

    def test_late_visible_rename_claims_exactly_once(self, tmp_path):
        """Rename-visible-late: the batch exists on the writer's view
        but the reader's scan cannot see it yet. The claim simply comes
        up empty — and the first scan after propagation claims every
        record exactly once."""
        shared = tmp_path / "spool"
        writer = Spool(shared)
        reader = Spool(shared, create=False)
        # The writer's rename has not propagated: model the reader's
        # stale directory cache by parking the batch outside requests/.
        writer.enqueue_batch(_recs(6))
        (batch,) = list(writer.requests.glob("*.jsonb"))
        hidden = tmp_path / "in-flight" / batch.name
        hidden.parent.mkdir()
        batch.rename(hidden)
        assert reader.claim(16) == []  # not visible yet: empty, not torn
        hidden.rename(batch)  # the attribute cache expires
        got = reader.claim(16)
        assert sorted(r["id"] for r in got) == [f"r{i:04d}" for i in range(6)]
        assert reader.claim(16) == []

    def test_size_before_content_recovers_tail_without_dup(self, tmp_path):
        """Size-visible-before-content: the reader sees the batch at
        its final length but the tail pages are still zeros. The crc
        framing drops the unpropagated tail as torn (prefix records
        serve immediately); once the content lands, the recover path
        re-claims the batch and serves ONLY the records that were
        never answered — exactly-once across the lag."""
        shared = tmp_path / "spool"
        writer = Spool(shared)
        writer.enqueue_batch(_recs(8))
        (batch,) = list(writer.requests.glob("*.jsonb"))
        full = batch.read_bytes()
        # Frame boundary of record 5: final size, zeroed tail.
        cut = full.find(b"\n", full.find(b"r0005")) + 1
        batch.write_bytes(full[:cut] + b"\x00" * (len(full) - cut))

        reader = Spool(shared, create=False)
        first = reader.claim(16)
        assert [r["id"] for r in first] == [f"r{i:04d}" for i in range(6)]
        # Half the prefix answers before the tail pages land (the lag
        # window is real time; serving is too).
        for r in first[:3]:
            assert reader.respond_once(r["id"], {"id": r["id"], "tokens": [1]})

        # The data pages propagate: the claimed file fills in under the
        # same name (same inode on the shared filesystem).
        (claimed,) = list(reader.claimed.glob("*.jsonb"))
        claimed.write_bytes(full)

        # Next engine life walks the recover path and re-claims: the
        # answered prefix is deduped, the unanswered rest — including
        # the late tail — is served now.
        second_life = Spool(shared, create=False)
        assert second_life.recover_claimed() == 8
        again = second_life.claim(16)
        assert sorted(r["id"] for r in again) == [
            f"r{i:04d}" for i in range(3, 8)
        ]
        # The answered prefix kept exactly one response each; the tail
        # publishes exactly once too.
        for r in again:
            assert second_life.respond_once(
                r["id"], {"id": r["id"], "tokens": [2]}
            )
        for i in range(8):
            assert second_life.read_response(f"r{i:04d}") is not None
        assert not second_life.respond_once("r0000", {"id": "r0000"})


# ---- syscall budget ----


class TestSyscallBudget:
    def test_batched_enqueue_within_quarter_of_unbatched(self, tmp_path):
        """The bar the batching exists to clear: a 64-request burst
        through enqueue_batch costs <= 1/4 the spool ops of 64
        per-file enqueues — producer side AND the consumer's claim."""
        burst = _recs(64)
        single = Spool(tmp_path / "single")
        for r in burst:
            single.enqueue(dict(r))
        single.claim(64)
        single_ops = single.io.total()

        batched = Spool(tmp_path / "batched")
        batched.enqueue_batch([dict(r) for r in burst])
        got = batched.claim(64)
        assert len(got) == 64
        batched_ops = batched.io.total()
        assert batched_ops * 4 <= single_ops, (
            f"batched={batched.io.snapshot()} single={single.io.snapshot()}"
        )

    def test_wait_response_polls_follow_backoff(self, tmp_path):
        """An absent response polled for 0.6 s costs tens of stats on
        the adaptive schedule, not timeout/interval of them."""
        sp = Spool(tmp_path / "spool")
        with pytest.raises(TimeoutError):
            sp.wait_response("nope", timeout=0.6)
        # Fixed 5 ms polling would be ~120; the 2 ms -> 250 ms schedule
        # reaches the cap within ~10 polls.
        assert sp.io.polls <= 40, sp.io.snapshot()


# ---- shm ring primitive ----


class TestShmRing:
    def test_roundtrip_through_many_wraparounds(self, tmp_path):
        """Push/pop far more bytes than the capacity: order preserved,
        nothing lost, nothing duplicated, wrap markers invisible."""
        ring = ShmRing.create(tmp_path / "t.ring", capacity=4096)
        sent, got = [], []
        for i in range(400):
            payload = json.dumps({"i": i, "pad": "x" * (i % 97)}).encode()
            while not ring.push(payload):
                got.extend(ring.pop())
            sent.append(payload)
        got.extend(ring.pop())
        assert got == sent
        assert ring.torn == 0
        assert ring.used == 0
        ring.close()

    def test_full_ring_signals_spill_then_recovers(self, tmp_path):
        ring = ShmRing.create(tmp_path / "t.ring", capacity=4096)
        payload = b"y" * 512
        pushed = 0
        while ring.push(payload):
            pushed += 1
        assert 0 < pushed < 16
        assert ring.push_full >= 1
        assert len(ring.pop()) == pushed
        assert ring.push(payload)  # space again after the drain
        ring.close()

    def test_corrupt_payload_counts_torn_and_skips(self, tmp_path):
        ring = ShmRing.create(tmp_path / "t.ring", capacity=4096)
        ring.push(b"first-record")
        ring.push(b"second-record")
        # Corrupt the FIRST record's payload in place (a second mapping
        # of the same file, as a hostile writer would be).
        other = ShmRing.attach(tmp_path / "t.ring")
        other._mm[HEADER_BYTES + REC_HEADER.size] ^= 0xFF
        out = ring.pop()
        assert out == [b"second-record"]
        assert ring.torn == 1
        other.close()
        ring.close()

    def test_state_survives_reattach(self, tmp_path):
        """Cursors live in the mmap'd file: records pushed before a
        consumer restart are delivered after it, exactly once."""
        a = ShmRing.create(tmp_path / "t.ring", capacity=4096)
        a.push(b"one")
        a.push(b"two")
        a.close()
        b = ShmRing.attach(tmp_path / "t.ring")
        assert b.pop() == [b"one", b"two"]
        assert b.pop() == []
        b.close()


# ---- engine transport: fallback ladder ----


class TestEngineTransport:
    def test_file_path_first_class_until_rings_exist(self, tmp_path):
        """shmring transport with no router rings yet behaves exactly
        like the file spool — then attaches when the router creates
        the pair and drains the ring tier first."""
        root = tmp_path / "spool"
        et = EngineTransport(root, "shmring")
        Spool(root).enqueue(_recs(1, "file")[0])
        polled, from_ring = et.poll_requests(8)
        assert [r["id"] for r in polled] == ["file0000"]
        assert from_ring == 0 and not et.ring_attached

        port = RouterRingPort(root)
        assert port.send(_recs(1, "ring")[0])
        polled, from_ring = et.poll_requests(8)
        assert [r["id"] for r in polled] == ["ring0000"]
        assert from_ring == 1 and et.ring_attached
        et.close()
        port.close()

    def test_response_ring_full_spills_to_file_exactly_once(self, tmp_path):
        """Responses overflow a tiny ring into the file path; the
        router-side collection (ring drain + file drain) sees every
        response exactly once."""
        root = tmp_path / "spool"
        port = RouterRingPort(root, capacity=4096)
        et = EngineTransport(root, "shmring")
        et.poll_requests(1)  # forces the attach
        assert et.ring_attached
        n = 48
        for i in range(n):
            et.respond(f"q{i:04d}", {"id": f"q{i:04d}", "pad": "z" * 200})
        assert et.ring_send_spills > 0, "ring never filled; shrink it"
        assert et.ring_sends > 0
        got = [r["id"] for r in port.recv()]
        got += [r["id"] for r in Spool(root).drain_responses()]
        assert sorted(got) == [f"q{i:04d}" for i in range(n)]
        et.close()
        port.close()

    def test_idle_spool_scans_back_off_behind_the_ring(self, tmp_path):
        """With a ring attached, an idle engine's file-spool scans are
        gated by the shared backoff — polling hard for 0.3 s costs a
        handful of scandirs, not one per poll. Zero ring traffic
        means zero ring receives (the idle-zero pin, memory tier)."""
        root = tmp_path / "spool"
        port = RouterRingPort(root)
        et = EngineTransport(root, "shmring")
        et.poll_requests(1)
        assert et.ring_attached
        scans0 = et.spool.io.scans
        polls = 0
        deadline = time.monotonic() + 0.3
        while time.monotonic() < deadline:
            recs, _ = et.poll_requests(8)
            assert recs == []
            polls += 1
        assert polls > 200  # the loop really did spin
        assert et.spool.io.scans - scans0 <= 12, et.spool.io.snapshot()
        assert et.ring_recvs == 0
        et.close()
        port.close()


# ---- router over the ring tier (no subprocesses) ----


class _Handle:
    def __init__(self, rtype=ReplicaType.MASTER, index=0, active=True):
        self.replica_type = rtype
        self.index = index
        self._active = active

    def is_active(self):
        return self._active


def _ring_job(replicas=1, shards=0, **slo):
    return serveplane_bench._make_serve_job(
        "svc", replicas, slots=4, tpot_ms=10.0, idle_timeout=0.0,
        max_queue_depth=slo.get("max_queue_depth", 0),
        deadline_s=slo.get("deadline_s", 0.0),
        retry_limit=slo.get("retry_limit", 2),
        transport="shmring", router_shards=shards,
    )


def _handles(n):
    out = [_Handle(ReplicaType.MASTER, 0)]
    out += [_Handle(ReplicaType.WORKER, i) for i in range(n - 1)]
    return out


class TestRouterRingTier:
    def test_ring_dispatch_and_publish_once(self, tmp_path):
        """The straight-line memory path: front submit -> router sends
        over the replica's req ring -> engine answers over the resp
        ring -> router publishes to the front spool, once."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _ring_job()
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.submit(prompt_len=2, max_new_tokens=4)
        router.tick(key, job, _handles(1), {})
        io = router.io_snapshot()
        assert io["ring_sends"] == 1, io

        eng = EngineRingPort.attach(
            replica_spool_dir(serve_root_dir(state), key, "Master", 0)
        )
        (req,) = eng.recv()
        assert req["id"] == rid and req["attempts"] == 1
        eng.send({"id": rid, "tokens": [7], "ttft_ms": 1.0})
        router.tick(key, job, _handles(1), {})
        resp = front.read_response(rid)
        assert resp is not None and resp["tokens"] == [7]
        assert resp["attempts"] == 1
        assert [p.stem for p in front.responses.glob("*.json")] == [rid]
        # No file-spool traffic rode along: the replica spool is empty.
        rsp = Spool(replica_spool_dir(serve_root_dir(state), key, "Master", 0))
        assert rsp.pending_count() == 0
        eng.close()
        router.close()

    def test_ring_peer_kill_respills_exactly_once(self, tmp_path):
        """A ring peer SIGKILLed after CONSUMING a request (the
        at-most-once window the ring explicitly does not cover): the
        router's death pass re-drives the request to a live replica,
        and when the dead peer's answer later surfaces anyway, the
        front-spool publication point dedups it."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _ring_job(replicas=2, retry_limit=3)
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rid = front.submit(prompt_len=2, max_new_tokens=4)
        handles = _handles(2)
        router.tick(key, job, handles, {})
        assert router.io_snapshot()["ring_sends"] == 1

        # Which replica got it? Consume there, then kill that handle.
        serve_root = serve_root_dir(state)
        victim = None
        for h in handles:
            port = EngineRingPort.attach(
                replica_spool_dir(serve_root, key, h.replica_type.value, h.index)
            )
            reqs = port.recv()
            if reqs:
                victim = (h, port, reqs[0])
            else:
                port.close()
        assert victim is not None
        dead_handle, dead_port, req = victim
        assert req["id"] == rid
        dead_handle._active = False

        # Retry backoff is ~50 ms; tick until the re-route lands
        # somewhere alive (ring or file spill both count).
        survivor = next(h for h in handles if h is not dead_handle)
        sp = Spool(replica_spool_dir(
            serve_root, key, survivor.replica_type.value, survivor.index
        ))
        eng = EngineRingPort.attach(sp.root)
        redelivered = None
        deadline = time.monotonic() + 5.0
        while redelivered is None and time.monotonic() < deadline:
            router.tick(key, job, handles, {})
            ring_reqs = eng.recv()
            file_reqs = sp.claim(4)
            for r in ring_reqs + file_reqs:
                if r["id"] == rid:
                    redelivered = r
            time.sleep(0.02)
        assert redelivered is not None, "re-route never reached the survivor"
        assert redelivered["attempts"] == 2
        assert router.io_snapshot()["ring_sends"] >= 1

        # The survivor answers; the publication sticks.
        eng.send({"id": rid, "tokens": [1, 2], "ttft_ms": 2.0})
        deadline = time.monotonic() + 5.0
        while not front.has_response(rid) and time.monotonic() < deadline:
            router.tick(key, job, handles, {})
            time.sleep(0.02)
        assert front.read_response(rid)["tokens"] == [1, 2]

        # The dead peer resurrects and answers LATE over its ring; the
        # router must collect it (consume-once) and lose the
        # publication race — one response file, the survivor's.
        dead_port.send({"id": rid, "tokens": [9, 9], "ttft_ms": 99.0})
        dead_handle._active = True
        for _ in range(5):
            router.tick(key, job, handles, {})
            time.sleep(0.02)
        assert [p.stem for p in front.responses.glob("*.json")] == [rid]
        assert front.read_response(rid)["tokens"] == [1, 2]
        dead_port.close()
        eng.close()
        router.close()


# ---- sharded router ----


class TestShardedRouter:
    def test_shard_of_is_stable_and_covering(self):
        rids = [f"req-{i}" for i in range(256)]
        owners = [shard_of(r, 4) for r in rids]
        assert owners == [shard_of(r, 4) for r in rids]  # deterministic
        assert set(owners) == {0, 1, 2, 3}  # every lane gets work
        assert all(shard_of(r, 1) == 0 for r in rids)

    def test_sharded_exactly_once_and_bounded_idle_scans(self, tmp_path):
        """Two worker lanes, one replica, 24 requests answered by an
        in-test engine loop: every submit published exactly once, lane
        handoffs invisible to the client, and an idle second afterward
        costs a bounded number of front scans (the backoff cap, not
        one scan per worker pass)."""
        state = tmp_path / "state"
        key = "default/svc"
        job = _ring_job(replicas=1, shards=2)
        router = ServeRouter(state)
        front = Spool(front_spool_dir(serve_root_dir(state), key, job.spec.serving))
        rids = [front.submit(prompt_len=2, max_new_tokens=4) for _ in range(24)]
        assert len({shard_of(r, 2) for r in rids}) == 2, "want both lanes hit"

        stop = threading.Event()

        def engine():
            port = None
            sp = Spool(replica_spool_dir(serve_root_dir(state), key, "Master", 0))
            while not stop.is_set():
                if port is None:
                    port = EngineRingPort.attach(sp.root)
                recs = (port.recv(8) if port else []) + sp.claim(8)
                for rec in recs:
                    resp = {"id": rec["id"], "tokens": [0], "ttft_ms": 1.0}
                    if not (port and port.send(resp)):
                        sp.respond(rec["id"], resp)
                time.sleep(0.005)
            if port:
                port.close()

        t = threading.Thread(target=engine, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline:
                summary = router.tick(key, job, _handles(1), {})
                if all(front.has_response(r) for r in rids):
                    break
                time.sleep(0.02)
            assert all(front.has_response(r) for r in rids)
            assert summary["shards"] == 2
            io = router.io_snapshot()
            assert io["shard_passes"] > 0
            assert io["dispatches"] >= 24

            # One response file per rid — no duplicate publications.
            files = sorted(p.stem for p in front.responses.glob("*.json"))
            assert files == sorted(rids)

            # Idle window: workers keep running; scans must be gated.
            io0 = router.io_snapshot()
            time.sleep(1.0)
            io1 = router.io_snapshot()
            assert io1["front_scans"] - io0["front_scans"] <= 30, (io0, io1)
            assert io1["ring_sends"] == io0["ring_sends"]
            assert io1["ring_recvs"] == io0["ring_recvs"]
        finally:
            stop.set()
            t.join(timeout=5.0)
            router.close()


# ---- chaos on the ring path, through the real stack ----


class TestRingChaosSmoke:
    def test_saturation_smoke_kill_replica_ring_exactly_once(self, tmp_path):
        """The bench's router-saturation shape at smoke scale: shmring
        transport, sharded router, subprocess replicas, kill_replica
        chaos — exactly-once must hold on the memory tier too."""
        cell = serveplane_bench.bench_cell(
            2,
            "kill_replica",
            rate=80.0,
            duration=2.0,
            slots=8,
            tpot_ms=2.0,
            max_new_tokens=4,
            max_queue_depth=256,
            deadline_s=8.0,
            retry_limit=3,
            idle_timeout=2.5,
            state_dir=tmp_path / "state",
            transport="shmring",
            router_shards=2,
            label="sat_smoke_killx2",
            log=lambda *_: None,
        )
        assert cell["transport"] == "shmring"
        assert cell["router_shards"] == 2
        assert cell["duplicates"] == 0, cell
        assert cell["lost"] == 0, cell
        assert cell["accounted"] == cell["offered"], cell
        assert cell["ok"] >= 1, cell
        io = cell["router_io"]
        assert io["ring_sends"] >= 1, io  # traffic really rode the ring
        assert io["shard_passes"] >= 1, io
