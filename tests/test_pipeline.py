"""Pipeline parallelism (pp axis) tests on the 8-device CPU mesh."""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.parallel import make_mesh
from pytorch_operator_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    """One residual MLP stage: x + tanh(x @ w + b)."""
    import jax.numpy as jnp

    return x + jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": (rng.standard_normal((n_stages, d, d)) * 0.3).astype(np.float32),
        "b": (rng.standard_normal((n_stages, d)) * 0.1).astype(np.float32),
    }


def _sequential_ref(params, x):
    import jax

    for i in range(params["w"].shape[0]):
        x = _stage_fn(jax.tree.map(lambda l: l[i], params), x)
    return x


class TestPipelineForward:
    @pytest.mark.parametrize("pp,microbatches", [(4, 4), (4, 8), (8, 8), (2, 2), (2, 8)])
    def test_matches_sequential(self, pp, microbatches):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh(f"pp={pp}", devices=jax.devices()[:pp])
        params = _stacked_params(pp, 8)
        x = np.random.default_rng(1).standard_normal((16, 8)).astype(np.float32)

        out = pipeline_apply(
            _stage_fn,
            jax.tree.map(jnp.asarray, params),
            jnp.asarray(x),
            mesh=mesh,
            microbatches=microbatches,
        )
        ref = _sequential_ref(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_under_jit(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 8))
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((8, 8)).astype(np.float32)
        )

        @jax.jit
        def f(params, x):
            return pipeline_apply(
                _stage_fn, params, x, mesh=mesh, microbatches=4
            ).sum()

        ref = float(_sequential_ref(jax.tree.map(np.asarray, params), np.asarray(x)).sum())
        assert float(f(params, x)) == pytest.approx(ref, rel=1e-5)


class TestPipelineBackward:
    @pytest.mark.slow
    def test_grads_match_sequential(self):
        """Autodiff through the pipeline = the reverse schedule; grads must
        equal the unpipelined model's."""
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 6, seed=3))
        x = jnp.asarray(
            np.random.default_rng(4).standard_normal((8, 6)).astype(np.float32)
        )

        def loss_pipe(params):
            return (
                pipeline_apply(_stage_fn, params, x, mesh=mesh, microbatches=4) ** 2
            ).mean()

        def loss_seq(params):
            return (_sequential_ref(params, x) ** 2).mean()

        gp = jax.grad(loss_pipe)(params)
        gs = jax.grad(loss_seq)(params)
        np.testing.assert_allclose(
            np.asarray(gp["w"]), np.asarray(gs["w"]), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(gp["b"]), np.asarray(gs["b"]), rtol=1e-4, atol=1e-5
        )


def _toy_loss(lp, y, tgt):
    """Cheap 'tail': linear head + squared error, mean over the mb."""
    import jax.numpy as jnp

    return ((y @ lp["head"] - tgt) ** 2).mean()


class TestPipeline1F1B:
    def _setup(self, pp, d=6, B=16, seed=5):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh(f"pp={pp}", devices=jax.devices()[:pp])
        params = jax.tree.map(jnp.asarray, _stacked_params(pp, d, seed=seed))
        rng = np.random.default_rng(seed + 1)
        x = jnp.asarray(rng.standard_normal((B, d)).astype(np.float32))
        tgt = jnp.asarray(rng.standard_normal((B, 3)).astype(np.float32))
        lp = {"head": jnp.asarray(rng.standard_normal((d, 3)).astype(np.float32))}
        return mesh, params, lp, x, tgt

    @pytest.mark.parametrize("pp,microbatches", [(4, 4), (4, 8), (2, 8), (8, 8)])
    def test_matches_sequential_autodiff(self, pp, microbatches):
        """1F1B loss AND every gradient (stage params, loss params, input)
        must equal plain jax.grad of the unpipelined model — the fused
        fwd/bwd scan is an execution order, not a numerics change."""
        import jax

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        mesh, params, lp, x, tgt = self._setup(pp)
        M = microbatches

        loss, (dsp, dlp, dx) = jax.jit(
            lambda p, l, xx: pipeline_value_and_grad(
                _stage_fn, _toy_loss, p, l, xx, tgt,
                mesh=mesh, microbatches=M, schedule="1f1b",
            )
        )(params, lp, x)

        def seq_loss(p, l, xx):
            import jax.numpy as jnp

            y = _sequential_ref(p, xx)
            ym = y.reshape((M, y.shape[0] // M) + y.shape[1:])
            tm = tgt.reshape((M, tgt.shape[0] // M) + tgt.shape[1:])
            return jnp.mean(
                jax.vmap(lambda a, b: _toy_loss(l, a, b))(ym, tm)
            )

        ref_loss, (rsp, rlp, rdx) = jax.value_and_grad(
            seq_loss, argnums=(0, 1, 2)
        )(params, lp, x)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        for got, ref in ((dsp, rsp), (dlp, rlp), (dx, rdx)):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
                ),
                got,
                ref,
            )

    def test_gpipe_schedule_matches_1f1b(self):
        """The two schedules are the same math: value_and_grad must agree
        leaf for leaf."""
        import jax

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        mesh, params, lp, x, tgt = self._setup(4)
        out = {}
        for sched in ("gpipe", "1f1b"):
            out[sched] = jax.jit(
                lambda p, l, xx, _s=sched: pipeline_value_and_grad(
                    _stage_fn, _toy_loss, p, l, xx, tgt,
                    mesh=mesh, microbatches=8, schedule=_s,
                )
            )(params, lp, x)
        assert float(out["gpipe"][0]) == pytest.approx(
            float(out["1f1b"][0]), rel=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            out["gpipe"][1],
            out["1f1b"][1],
        )

    def test_bad_schedule_rejected(self):
        import jax

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        mesh, params, lp, x, tgt = self._setup(2)
        with pytest.raises(ValueError, match="schedule"):
            pipeline_value_and_grad(
                _stage_fn, _toy_loss, params, lp, x, tgt,
                mesh=mesh, microbatches=4, schedule="interleaved",
            )

    def test_backward_residency_bounded_by_depth_not_microbatches(self):
        """THE 1F1B property (VERDICT r2 Missing #4): per-stage saved
        state is a depth-2P input ring, independent of M, while GPipe's
        backward holds residuals for all M microbatches per stage. Pinned
        two ways: (a) at M >> P the 1f1b compiled program's temp stays
        under GPipe's, and (b) quadrupling M moves 1f1b's temp only by
        the O(M/P) stream shards, NOT by M x per-tick residuals (GPipe's
        growth is several x larger)."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        P_, d = 4, 32
        mesh = make_mesh(f"pp={P_}", devices=jax.devices()[:P_])
        params = jax.tree.map(jnp.asarray, _stacked_params(P_, d))
        lp = {"head": jnp.zeros((d, 3), jnp.float32)}

        def temp_bytes(schedule, M, B):
            x = jnp.zeros((B, d), jnp.float32)
            tgt = jnp.zeros((B, 3), jnp.float32)
            f = jax.jit(
                lambda p, l, xx: pipeline_value_and_grad(
                    _stage_fn, _toy_loss, p, l, xx, tgt,
                    mesh=mesh, microbatches=M, schedule=schedule,
                )
            )
            ma = f.lower(params, lp, x).compile().memory_analysis()
            if ma is None:
                pytest.skip("backend exposes no compiled memory analysis")
            return ma.temp_size_in_bytes

        mb_bytes = 4 * d * 4  # fixed per-mb bytes: B/M is held at 4 below
        g16, g64 = temp_bytes("gpipe", 16, 64), temp_bytes("gpipe", 64, 256)
        f16, f64 = temp_bytes("1f1b", 16, 64), temp_bytes("1f1b", 64, 256)
        # (a) at M=64 >> P=4 the fused schedule must be the smaller program
        assert f64 < g64, (f64, g64)
        # (b) GPipe backward residency grows with M (48 extra microbatch
        # residuals per stage at minimum); 1f1b's growth is stream-only —
        # bounded by the extra in/out/dx shards (3 streams x 48/P mbs),
        # nowhere near GPipe's.
        assert g64 - g16 > 48 * mb_bytes, (g16, g64)
        assert f64 - f16 < (g64 - g16) / 2, (f16, f64, g16, g64)


def _sharded_toy_loss(kp):
    """Column-chunked 'tail' for sharded_loss=True: each stage owns kp
    columns of the head and the targets; partial squared errors combine
    with one psum — the toy analog of a vocab-parallel xent."""

    def loss(lp_local, y, tgt):
        import jax
        import jax.numpy as jnp

        off = jax.lax.axis_index("pp") * kp
        tgt_local = jax.lax.dynamic_slice_in_dim(tgt, off, kp, 1)
        partial = ((y @ lp_local["head"] - tgt_local) ** 2).sum()
        return jax.lax.psum(partial, "pp") / (tgt.shape[0] * tgt.shape[1])

    return loss


class TestPipelineShardedLoss:
    """sharded_loss=True: the loss tail is partitioned over pp instead of
    duplicated P-fold (round-4 VERDICT Missing #2)."""

    def _setup(self, pp, d=6, K=8, B=16, seed=7):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh(f"pp={pp}", devices=jax.devices()[:pp])
        params = jax.tree.map(jnp.asarray, _stacked_params(pp, d, seed=seed))
        rng = np.random.default_rng(seed + 1)
        x = jnp.asarray(rng.standard_normal((B, d)).astype(np.float32))
        tgt = jnp.asarray(rng.standard_normal((B, K)).astype(np.float32))
        head = rng.standard_normal((d, K)).astype(np.float32)
        kp = K // pp
        # [P, d, K/P] — stage s owns columns [s*kp, (s+1)*kp).
        lp = {"head": jnp.moveaxis(jnp.asarray(head).reshape(d, pp, kp), 1, 0)}
        return mesh, params, lp, head, x, tgt, kp

    @pytest.mark.parametrize("pp,microbatches", [(4, 4), (4, 8), (2, 8)])
    def test_matches_sequential_autodiff(self, pp, microbatches):
        """Chunked-loss 1F1B loss AND gradients (stage params, chunked
        loss params, input) must equal plain autodiff of the unpipelined
        model with the unchunked head."""
        import jax
        import jax.numpy as jnp

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        mesh, params, lp, head, x, tgt, kp = self._setup(pp)
        M = microbatches

        loss, (dsp, dlp, dx) = jax.jit(
            lambda p, l, xx: pipeline_value_and_grad(
                _stage_fn, _sharded_toy_loss(kp), p, l, xx, tgt,
                mesh=mesh, microbatches=M, schedule="1f1b",
                sharded_loss=True,
            )
        )(params, lp, x)

        def seq_loss(p, h, xx):
            y = _sequential_ref(p, xx)
            ym = y.reshape((M, y.shape[0] // M) + y.shape[1:])
            tm = tgt.reshape((M, tgt.shape[0] // M) + tgt.shape[1:])
            per_mb = jax.vmap(
                lambda a, b: ((a @ h - b) ** 2).mean()
            )(ym, tm)
            return jnp.mean(per_mb)

        ref_loss, (rsp, rh, rdx) = jax.value_and_grad(
            seq_loss, argnums=(0, 1, 2)
        )(params, jnp.asarray(head), x)
        assert float(loss) == pytest.approx(float(ref_loss), rel=1e-5)
        # Reassemble the chunked head grad to compare against the full one.
        d_head = np.asarray(
            jnp.moveaxis(dlp["head"], 0, 1).reshape(head.shape)
        )
        np.testing.assert_allclose(d_head, np.asarray(rh), rtol=1e-4, atol=1e-5)
        for got, ref in ((dsp, rsp), (dx, rdx)):
            jax.tree.map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
                ),
                got,
                ref,
            )

    def test_gpipe_schedule_matches_1f1b(self):
        """Both schedules accept the sharded-loss contract and must agree
        leaf for leaf (including the chunked d_loss_params layout)."""
        import jax

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        mesh, params, lp, _head, x, tgt, kp = self._setup(4)
        out = {}
        for sched in ("gpipe", "1f1b"):
            out[sched] = jax.jit(
                lambda p, l, xx, _s=sched: pipeline_value_and_grad(
                    _stage_fn, _sharded_toy_loss(kp), p, l, xx, tgt,
                    mesh=mesh, microbatches=8, schedule=_s,
                    sharded_loss=True,
                )
            )(params, lp, x)
        assert float(out["gpipe"][0]) == pytest.approx(
            float(out["1f1b"][0]), rel=1e-5
        )
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5
            ),
            out["gpipe"][1],
            out["1f1b"][1],
        )

    def test_unchunked_loss_params_rejected(self):
        import jax

        from pytorch_operator_tpu.parallel.pipeline import pipeline_value_and_grad

        mesh, params, _lp, head, x, tgt, kp = self._setup(4)
        for sched in ("gpipe", "1f1b"):
            with pytest.raises(ValueError, match="stage-chunked"):
                pipeline_value_and_grad(
                    _stage_fn, _sharded_toy_loss(kp), params,
                    {"head": jax.numpy.asarray(head)},  # no leading P axis
                    x, tgt, mesh=mesh, microbatches=8, schedule=sched,
                    sharded_loss=True,
                )


class TestPipelineValidation:
    def test_bad_microbatch_split_rejected(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 4))
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(
                _stage_fn, params, jnp.zeros((10, 4)), mesh=mesh, microbatches=3
            )

    def test_stage_count_mismatch_rejected(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(3, 4))
        with pytest.raises(ValueError, match="pp extent"):
            pipeline_apply(
                _stage_fn, params, jnp.zeros((8, 4)), mesh=mesh, microbatches=4
            )

    def test_microbatches_not_divisible_by_stages_rejected(self):
        """The microbatch stream is sharded over pp, so M % P == 0."""
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 4))
        with pytest.raises(ValueError, match="pp extent"):
            pipeline_apply(
                _stage_fn, params, jnp.zeros((12, 4)), mesh=mesh, microbatches=6
            )


class TestPipelineMemory:
    def test_forward_activations_stay_stage_local(self):
        """Regression for the round-1 design, which replicated the FULL
        microbatch stream (input + output, 2*M microbatches) on every
        stage. The rewrite keeps O(M/P) stream shards + O(1) transit
        microbatches per device, so the compiled program's per-device
        temp must fit under M * microbatch_bytes — a bound the round-1
        program exceeded (measured at this exact config: old 8328+ bytes
        scaling with B; the sharded rewrite 4560, scaling with B/P; at
        the larger config below, old ~2x the threshold)."""
        import jax
        import jax.numpy as jnp

        P_, d, B, M = 4, 32, 256, 16
        mesh = make_mesh(f"pp={P_}", devices=jax.devices()[:P_])
        params = jax.tree.map(jnp.asarray, _stacked_params(P_, d))
        x = jnp.zeros((B, d), jnp.float32)

        f = jax.jit(
            lambda p, x: pipeline_apply(
                _stage_fn, p, x, mesh=mesh, microbatches=M
            )
        )
        ma = f.lower(params, x).compile().memory_analysis()
        if ma is None:
            pytest.skip("backend exposes no compiled memory analysis")
        mb_bytes = (B // M) * d * 4
        assert ma.temp_size_in_bytes < M * mb_bytes, (
            f"per-device temp {ma.temp_size_in_bytes}B >= {M * mb_bytes}B "
            "— the pipeline is carrying a full replicated microbatch "
            "stream again"
        )
