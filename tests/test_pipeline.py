"""Pipeline parallelism (pp axis) tests on the 8-device CPU mesh."""

from __future__ import annotations

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.parallel import make_mesh
from pytorch_operator_tpu.parallel.pipeline import pipeline_apply


def _stage_fn(params, x):
    """One residual MLP stage: x + tanh(x @ w + b)."""
    import jax.numpy as jnp

    return x + jnp.tanh(x @ params["w"] + params["b"])


def _stacked_params(n_stages, d, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "w": (rng.standard_normal((n_stages, d, d)) * 0.3).astype(np.float32),
        "b": (rng.standard_normal((n_stages, d)) * 0.1).astype(np.float32),
    }


def _sequential_ref(params, x):
    import jax

    for i in range(params["w"].shape[0]):
        x = _stage_fn(jax.tree.map(lambda l: l[i], params), x)
    return x


class TestPipelineForward:
    @pytest.mark.parametrize("pp,microbatches", [(4, 4), (4, 8), (8, 8), (2, 2), (2, 8)])
    def test_matches_sequential(self, pp, microbatches):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh(f"pp={pp}", devices=jax.devices()[:pp])
        params = _stacked_params(pp, 8)
        x = np.random.default_rng(1).standard_normal((16, 8)).astype(np.float32)

        out = pipeline_apply(
            _stage_fn,
            jax.tree.map(jnp.asarray, params),
            jnp.asarray(x),
            mesh=mesh,
            microbatches=microbatches,
        )
        ref = _sequential_ref(params, x)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5)

    def test_under_jit(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 8))
        x = jnp.asarray(
            np.random.default_rng(2).standard_normal((8, 8)).astype(np.float32)
        )

        @jax.jit
        def f(params, x):
            return pipeline_apply(
                _stage_fn, params, x, mesh=mesh, microbatches=4
            ).sum()

        ref = float(_sequential_ref(jax.tree.map(np.asarray, params), np.asarray(x)).sum())
        assert float(f(params, x)) == pytest.approx(ref, rel=1e-5)


class TestPipelineBackward:
    def test_grads_match_sequential(self):
        """Autodiff through the pipeline = the reverse schedule; grads must
        equal the unpipelined model's."""
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 6, seed=3))
        x = jnp.asarray(
            np.random.default_rng(4).standard_normal((8, 6)).astype(np.float32)
        )

        def loss_pipe(params):
            return (
                pipeline_apply(_stage_fn, params, x, mesh=mesh, microbatches=4) ** 2
            ).mean()

        def loss_seq(params):
            return (_sequential_ref(params, x) ** 2).mean()

        gp = jax.grad(loss_pipe)(params)
        gs = jax.grad(loss_seq)(params)
        np.testing.assert_allclose(
            np.asarray(gp["w"]), np.asarray(gs["w"]), rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(gp["b"]), np.asarray(gs["b"]), rtol=1e-4, atol=1e-5
        )


class TestPipelineValidation:
    def test_bad_microbatch_split_rejected(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 4))
        with pytest.raises(ValueError, match="microbatches"):
            pipeline_apply(
                _stage_fn, params, jnp.zeros((10, 4)), mesh=mesh, microbatches=3
            )

    def test_stage_count_mismatch_rejected(self):
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(3, 4))
        with pytest.raises(ValueError, match="pp extent"):
            pipeline_apply(
                _stage_fn, params, jnp.zeros((8, 4)), mesh=mesh, microbatches=4
            )

    def test_microbatches_not_divisible_by_stages_rejected(self):
        """The microbatch stream is sharded over pp, so M % P == 0."""
        import jax
        import jax.numpy as jnp

        mesh = make_mesh("pp=4", devices=jax.devices()[:4])
        params = jax.tree.map(jnp.asarray, _stacked_params(4, 4))
        with pytest.raises(ValueError, match="pp extent"):
            pipeline_apply(
                _stage_fn, params, jnp.zeros((12, 4)), mesh=mesh, microbatches=6
            )


class TestPipelineMemory:
    def test_forward_activations_stay_stage_local(self):
        """Regression for the round-1 design, which replicated the FULL
        microbatch stream (input + output, 2*M microbatches) on every
        stage. The rewrite keeps O(M/P) stream shards + O(1) transit
        microbatches per device, so the compiled program's per-device
        temp must fit under M * microbatch_bytes — a bound the round-1
        program exceeded (measured at this exact config: old 8328+ bytes
        scaling with B; the sharded rewrite 4560, scaling with B/P; at
        the larger config below, old ~2x the threshold)."""
        import jax
        import jax.numpy as jnp

        P_, d, B, M = 4, 32, 256, 16
        mesh = make_mesh(f"pp={P_}", devices=jax.devices()[:P_])
        params = jax.tree.map(jnp.asarray, _stacked_params(P_, d))
        x = jnp.zeros((B, d), jnp.float32)

        f = jax.jit(
            lambda p, x: pipeline_apply(
                _stage_fn, p, x, mesh=mesh, microbatches=M
            )
        )
        ma = f.lower(params, x).compile().memory_analysis()
        if ma is None:
            pytest.skip("backend exposes no compiled memory analysis")
        mb_bytes = (B // M) * d * 4
        assert ma.temp_size_in_bytes < M * mb_bytes, (
            f"per-device temp {ma.temp_size_in_bytes}B >= {M * mb_bytes}B "
            "— the pipeline is carrying a full replicated microbatch "
            "stream again"
        )
