"""KV-cache autoregressive generation (workloads/generate.py).

The load-bearing property: greedy decode through the cache path must
reproduce the training model's full-forward argmax rollout token for
token — any cache-indexing, rope-position, or mask bug diverges the
sequences immediately.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401
from pytorch_operator_tpu.models import llama as llama_lib
from pytorch_operator_tpu.workloads.generate import init_cache, make_generate


def _setup(prompt_len=8, new=8, **cfg_over):
    import jax
    import jax.numpy as jnp

    cfg = llama_lib.llama_tiny(
        decode=True, max_decode_len=prompt_len + new, **cfg_over
    )
    train_model = llama_lib.Llama(dataclasses.replace(cfg, decode=False))
    decode_model = llama_lib.Llama(cfg)
    import flax.linen as nn

    params = nn.meta.unbox(
        train_model.init(jax.random.key(0), np.zeros((1, prompt_len), np.int32))[
            "params"
        ]
    )
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, prompt_len)),
        jnp.int32,
    )
    return cfg, train_model, decode_model, params, prompt


def _greedy_reference(train_model, params, prompt, new):
    """Naive rollout: full forward over the growing sequence each step."""
    import jax.numpy as jnp

    seq = prompt
    out = []
    for _ in range(new):
        logits = train_model.apply({"params": params}, seq)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


class TestGenerate:
    @pytest.mark.slow
    def test_greedy_cache_decode_matches_full_forward(self):
        import jax

        new = 8
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        gen = make_generate(decode_model, max_new_tokens=new)
        cache = init_cache(decode_model, prompt.shape[0], prompt.shape[1])
        toks, _ = gen(params, cache, prompt, jax.random.key(0))
        ref = _greedy_reference(train_model, params, prompt, new)
        np.testing.assert_array_equal(np.asarray(toks), np.asarray(ref))

    def test_temperature_sampling_runs_and_differs(self):
        import jax

        new = 8
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        greedy = make_generate(decode_model, max_new_tokens=new)
        hot = make_generate(decode_model, max_new_tokens=new, temperature=5.0)
        cache = init_cache(decode_model, prompt.shape[0], prompt.shape[1])
        g, _ = greedy(params, cache, prompt, jax.random.key(0))
        cache = init_cache(decode_model, prompt.shape[0], prompt.shape[1])
        h, _ = hot(params, cache, prompt, jax.random.key(0))
        assert g.shape == h.shape == (2, new)
        # At T=5 on random-init logits the samples must diverge from argmax.
        assert (np.asarray(g) != np.asarray(h)).any()

    def test_cache_overflow_rejected_at_trace_time(self):
        import jax
        import pytest

        cfg, train_model, decode_model, params, prompt = _setup(
            prompt_len=8, new=8
        )  # max_decode_len = 16
        gen = make_generate(decode_model, max_new_tokens=16)  # 8+16 > 16
        cache = init_cache(decode_model, prompt.shape[0], prompt.shape[1])
        with pytest.raises(ValueError, match="max_decode_len"):
            gen(params, cache, prompt, jax.random.key(0))

    def test_debug_checks_reject_ragged_positions(self, monkeypatch):
        """ADVICE r2: _decode_attend's batch-uniform-positions contract is
        silently wrong when violated (cache offset/mask read row 0); with
        TPUJOB_DEBUG_CHECKS=1 a ragged-prompt caller must get an error,
        not wrong attention."""
        import jax
        import jax.numpy as jnp
        import pytest

        monkeypatch.setenv("TPUJOB_DEBUG_CHECKS", "1")
        cfg, train_model, decode_model, params, prompt = _setup()
        # No cache passed: the flax apply path zero-initializes its own
        # scan-stacked cache under mutable (init_cache now produces the
        # decode_forward flat layout, which this path would ignore).
        ragged = jnp.stack(
            [jnp.arange(prompt.shape[1]), jnp.arange(prompt.shape[1]) + 1]
        ).astype(jnp.int32)
        with pytest.raises(Exception, match="batch-uniform"):
            out, _ = decode_model.apply(
                {"params": params},
                prompt,
                positions=ragged,
                mutable=["cache"],
            )
            jax.block_until_ready(out)
        # Uniform positions pass the guard unchanged.
        uniform = jnp.broadcast_to(
            jnp.arange(prompt.shape[1], dtype=jnp.int32), prompt.shape
        )
        out, _ = decode_model.apply(
            {"params": params},
            prompt,
            positions=uniform,
            mutable=["cache"],
        )
        jax.block_until_ready(out)

    def test_top_k_one_equals_greedy(self):
        """top_k=1 at any temperature collapses the distribution to the
        argmax — must reproduce the greedy rollout exactly."""
        import jax

        new = 8
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        greedy = make_generate(decode_model, max_new_tokens=new)
        k1 = make_generate(
            decode_model, max_new_tokens=new, temperature=2.0, top_k=1
        )
        g, _ = greedy(
            params,
            init_cache(decode_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(0),
        )
        t, _ = k1(
            params,
            init_cache(decode_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(0),
        )
        np.testing.assert_array_equal(np.asarray(g), np.asarray(t))

    def test_top_k_and_top_p_restrict_samples(self):
        """Sampled tokens must come from the allowed head of the
        distribution: with a tiny top_p every draw is (near-)argmax;
        invalid knob values are rejected up front."""
        import jax
        import pytest

        new = 8
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        # top_p -> 0 keeps only the top token (the implementation always
        # keeps at least one): equals greedy.
        p0 = make_generate(
            decode_model, max_new_tokens=new, temperature=3.0, top_p=1e-6
        )
        greedy = make_generate(decode_model, max_new_tokens=new)
        a, _ = p0(
            params,
            init_cache(decode_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(1),
        )
        b, _ = greedy(
            params,
            init_cache(decode_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(1),
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        with pytest.raises(ValueError, match="top_p"):
            make_generate(decode_model, max_new_tokens=new, top_p=0.0)
        with pytest.raises(ValueError, match="top_k"):
            make_generate(decode_model, max_new_tokens=new, top_k=-1)
        # Truncation knobs with T=0 would be silently ignored — reject.
        with pytest.raises(ValueError, match="temperature"):
            make_generate(decode_model, max_new_tokens=new, top_p=0.9)

    def test_top_p_near_one_composed_with_top_k_stays_in_range(self):
        """ADVICE r4: keep = sum(cum < top_p) can equal V when the float
        cumsum never reaches a top_p near 1.0 (and saturates early under
        a composed top_k); the cutoff gather is now explicitly clamped
        instead of leaning on gather's implicit clip mode. The edge case
        must sample valid in-range tokens."""
        import jax

        new = 8
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        gen = make_generate(
            decode_model, max_new_tokens=new, temperature=1.0,
            top_k=4, top_p=1.0 - 1e-12,
        )
        toks, _ = gen(
            params,
            init_cache(decode_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(3),
        )
        t = np.asarray(toks)
        assert t.shape == (2, new)
        assert ((t >= 0) & (t < cfg.vocab_size)).all()

    @pytest.mark.slow
    def test_flash_prefill_matches_dense_prefill(self):
        """Long-prompt serving: prefill runs causal self-attention over
        the prompt (flash when configured) instead of materializing
        scores against the whole cache budget. Flash and dense prefill
        must agree (same math, blockwise vs materialized) AND produce
        identical greedy rollouts on the tiny model."""
        import dataclasses

        import jax

        new = 6
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        flash_model = llama_lib.Llama(
            dataclasses.replace(decode_model.cfg, attn_impl="flash")
        )
        t_dense, _ = make_generate(decode_model, max_new_tokens=new)(
            params,
            init_cache(decode_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(0),
        )
        t_flash, _ = make_generate(flash_model, max_new_tokens=new)(
            params,
            init_cache(flash_model, prompt.shape[0], prompt.shape[1]),
            prompt,
            jax.random.key(0),
        )
        np.testing.assert_array_equal(np.asarray(t_flash), np.asarray(t_dense))

    def test_debug_checks_reject_nonzero_prefill_start(self, monkeypatch):
        """Prefill attends over the incoming tokens only — a chunked
        prefill (multi-token input at a nonzero start) would silently
        drop the earlier context, so debug mode rejects it."""
        import jax
        import jax.numpy as jnp
        import pytest

        monkeypatch.setenv("TPUJOB_DEBUG_CHECKS", "1")
        cfg, train_model, decode_model, params, prompt = _setup()
        shifted = jnp.broadcast_to(
            jnp.arange(2, 2 + prompt.shape[1], dtype=jnp.int32), prompt.shape
        )
        with pytest.raises(Exception, match="position 0"):
            out, _ = decode_model.apply(
                {"params": params},
                prompt,
                positions=shifted,
                mutable=["cache"],
            )
            jax.block_until_ready(out)
        # The SERVING path (decode_forward bypasses Llama.__call__) must
        # install the same guard.
        from pytorch_operator_tpu.models.llama import (
            decode_forward,
            init_decode_cache,
        )

        with pytest.raises(Exception, match="position 0"):
            out, _ = decode_forward(
                decode_model,
                params,
                init_decode_cache(decode_model.cfg, prompt.shape[0]),
                prompt,
                shifted,
            )
            jax.block_until_ready(out)

    def test_garbage_cache_contents_cannot_leak(self):
        """Every cache slot the mask allows reading is written by the
        current run first — a cache pre-filled with garbage must produce
        the same rollout as a zero cache (and the donated buffer from a
        previous run therefore can't leak either)."""
        import jax
        import jax.numpy as jnp

        new = 6
        cfg, train_model, decode_model, params, prompt = _setup(new=new)
        gen = make_generate(decode_model, max_new_tokens=new)
        clean = init_cache(decode_model, prompt.shape[0], prompt.shape[1])
        garbage = jax.tree.map(lambda z: jnp.full_like(z, 7.0), clean)
        t1, _ = gen(params, clean, prompt, jax.random.key(0))
        t2, _ = gen(params, garbage, prompt, jax.random.key(0))
        np.testing.assert_array_equal(np.asarray(t1), np.asarray(t2))
