"""Transformer model tests (Llama decoder, BERT encoder) on the 8-device
CPU mesh — sharded init via logical annotations, masking semantics, grad
flow, and remat equivalence.
"""

import numpy as np
import pytest

import tests.jaxenv  # noqa: F401

import jax
import jax.numpy as jnp

from pytorch_operator_tpu.models.bert import BertClassifier, bert_tiny
from pytorch_operator_tpu.models.llama import Llama, llama_tiny
from pytorch_operator_tpu.parallel import (
    activation_rules,
    init_sharded,
    make_mesh,
)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh({"dp": 2, "fsdp": 2, "tp": 2})


class TestLlama:
    @pytest.fixture(scope="class")
    def setup(self):
        cfg = llama_tiny()
        model = Llama(cfg)
        tokens = jax.random.randint(jax.random.key(1), (4, 16), 0, cfg.vocab_size)
        mesh = make_mesh({"dp": 2, "fsdp": 2, "tp": 2})
        variables, shardings = init_sharded(
            lambda k: model.init(k, tokens), mesh, jax.random.key(0)
        )
        return cfg, model, tokens, mesh, variables

    def test_params_sharded_fsdp_tp(self, setup):
        _, _, _, _, variables = setup
        p = variables["params"]
        q = p["layers"]["attn"]["q_proj"]["kernel"]
        # [layers, embed, heads, head_dim] → (None, fsdp, tp, None)
        assert tuple(q.sharding.spec) == (None, "fsdp", "tp", None)
        assert tuple(p["embed"]["embedding"].sharding.spec) == ("tp", "fsdp")
        assert tuple(p["layers"]["mlp"]["gate_proj"]["kernel"].sharding.spec) == (
            None, "fsdp", "tp",
        )

    def test_causal_mask(self, setup):
        cfg, model, tokens, mesh, variables = setup
        with mesh, activation_rules(mesh):
            base = jax.jit(model.apply)(variables, tokens)
            mutated = jax.jit(model.apply)(
                variables, tokens.at[:, 10].set((tokens[:, 10] + 1) % cfg.vocab_size)
            )
        np.testing.assert_allclose(
            np.asarray(base[:, :10]), np.asarray(mutated[:, :10]), atol=1e-5
        )
        assert float(jnp.abs(mutated[:, 10:] - base[:, 10:]).max()) > 1e-4

    def test_grad_flows_to_all_params(self, setup):
        cfg, model, tokens, mesh, variables = setup

        def loss(params):
            import optax

            with activation_rules(mesh):
                logits = model.apply({"params": params}, tokens)
            return optax.softmax_cross_entropy_with_integer_labels(
                logits[:, :-1], tokens[:, 1:]
            ).mean()

        with mesh:
            grads = jax.jit(jax.grad(loss))(variables["params"])
        zero = [
            path
            for path, g in jax.tree_util.tree_leaves_with_path(grads)
            if float(jnp.abs(g).max()) == 0.0
        ]
        assert not zero, f"dead params (no grad): {zero}"

    def test_remat_matches(self, setup):
        cfg, model, tokens, mesh, variables = setup
        remat_model = Llama(llama_tiny(remat=True))
        with mesh, activation_rules(mesh):
            a = jax.jit(model.apply)(variables, tokens)
            b = jax.jit(remat_model.apply)(variables, tokens)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)

    @pytest.mark.slow
    def test_flash_matches_dense(self, setup):
        """attn_impl='flash' (pallas kernel, sharded via shard_map over the
        dp/fsdp/tp mesh) reproduces the dense path's logits and grads."""
        cfg, model, tokens, mesh, variables = setup
        flash_model = Llama(llama_tiny(attn_impl="flash"), mesh=mesh)
        with mesh, activation_rules(mesh):
            a = jax.jit(model.apply)(variables, tokens)
            b = jax.jit(flash_model.apply)(variables, tokens)
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-4)

        def loss(m):
            def f(params):
                import optax

                with activation_rules(mesh):
                    logits = m.apply({"params": params}, tokens)
                return optax.softmax_cross_entropy_with_integer_labels(
                    logits[:, :-1], tokens[:, 1:]
                ).mean()

            return f

        with mesh:
            g_dense = jax.jit(jax.grad(loss(model)))(variables["params"])
            g_flash = jax.jit(jax.grad(loss(flash_model)))(variables["params"])
        for (path, gd), (_, gf) in zip(
            jax.tree_util.tree_leaves_with_path(g_dense),
            jax.tree_util.tree_leaves_with_path(g_flash),
        ):
            np.testing.assert_allclose(
                np.asarray(gd), np.asarray(gf), atol=5e-4, err_msg=str(path)
            )


class TestBert:
    def test_pad_mask_and_sharding(self, mesh):
        cfg = bert_tiny()
        model = BertClassifier(cfg, num_classes=3)
        tokens = jnp.ones((4, 32), jnp.int32)
        pad = jnp.arange(32)[None, :] < jnp.array([32, 20, 10, 5])[:, None]
        variables, _ = init_sharded(
            lambda k: model.init(k, tokens, None, pad), mesh, jax.random.key(0)
        )
        q = variables["params"]["bert"]["layers"]["attn"]["q_proj"]["kernel"]
        assert tuple(q.sharding.spec) == (None, "fsdp", "tp", None)
        with mesh, activation_rules(mesh):
            base = jax.jit(model.apply)(variables, tokens, None, pad)
            # mutating a PADDED position must not change any output
            l2 = jax.jit(model.apply)(
                variables, tokens.at[3, 20].set(7), None, pad
            )
            # mutating a REAL position must change row 0 (full length)
            l3 = jax.jit(model.apply)(
                variables, tokens.at[0, 1].set(7), None, pad
            )
        np.testing.assert_allclose(np.asarray(base), np.asarray(l2), atol=1e-5)
        assert float(jnp.abs(l3[0] - base[0]).max()) > 1e-6

    def test_single_device_mesh_still_works(self):
        """Annotations degrade to replication on a 1-axis mesh (TPU v5 lite)."""
        mesh = make_mesh({"dp": 8})
        cfg = bert_tiny()
        model = BertClassifier(cfg, num_classes=2)
        tokens = jnp.ones((8, 16), jnp.int32)
        variables, _ = init_sharded(
            lambda k: model.init(k, tokens), mesh, jax.random.key(0)
        )
        q = variables["params"]["bert"]["layers"]["attn"]["q_proj"]["kernel"]
        assert all(s is None for s in q.sharding.spec)  # fully replicated
        with mesh, activation_rules(mesh):
            out = jax.jit(model.apply)(variables, tokens)
        assert out.shape == (8, 2)
