"""Remediation engine (controller/remediation.py) tests.

- policy API: round-trip (presence-arms like serving), validation
  rejects bad bounds / unknown route rules / ambiguous routes, the
  policy threads into replica env;
- engine units (deterministic clock, no sleeps): slo_burn grows the
  serving replica set fast (doubling, clamped at scale_max), sustained
  idle shrinks slow (one seat, floored at scale_min, only while
  nothing fires), cooldown + backoff hysteresis gates repeats, the
  max_actions budget survives in the committed generation, dry-run
  writes the audit record but never touches spec or fleet, preempt
  resolves the alert's replica coordinate and SIGTERMs it post-commit,
  checkpoint_lag turns the async writer on exactly once, generic exec
  routes deliver the audit record;
- exactly-once under failover: a supervisor that dies in the
  commit→append window loses nothing (the adopter re-materialises the
  audit tail from the annotation and stays inside the dead owner's
  cooldown), and one that dies in the append→side-effect window of a
  scale-down has the seat delete healed — never re-decided;
- e2e: under a drop_heartbeat world with a LONG hang deadline, the
  remediation preempt recycles the silent replica and the job finishes
  — strictly faster than the hang-deadline kill, which never fires.
"""

from __future__ import annotations

import json
import sys
import time

import pytest

from pytorch_operator_tpu import faults
from pytorch_operator_tpu.api import (
    ObjectMeta,
    ProcessTemplate,
    RemediationPolicy,
    RemediationRoute,
    ReplicaSpec,
    ReplicaType,
    RestartPolicy,
    RunPolicy,
    TPUJob,
    TPUJobSpec,
    set_defaults,
    validate,
)
from pytorch_operator_tpu.api.defaults import (
    HANG_DEADLINE_ANNOTATION,
    LAST_REMEDIATION_ANNOTATION,
)
from pytorch_operator_tpu.api.serialization import job_from_dict
from pytorch_operator_tpu.api.types import ServingPolicy
from pytorch_operator_tpu.controller.remediation import (
    CKPT_CADENCE_ANNOTATION,
    load_remediation_log,
)
from pytorch_operator_tpu.controller.runner import FakeRunner
from pytorch_operator_tpu.controller.supervisor import Supervisor
from pytorch_operator_tpu.faults import Fault, FaultPlan
from pytorch_operator_tpu.obs.watch import Alert
from tests.testutil import new_job

T0 = 1000.0


def _rjob(name="serve", policy=None, workers=0, serving=True):
    job = new_job(name=name, workers=workers)
    if serving:
        job.spec.serving = ServingPolicy()
    job.spec.remediation = policy
    return job


def _alert(key, rule, replica="*", severity="critical", now=T0):
    return Alert(
        job=key, rule=rule, replica=replica, severity=severity,
        state="firing", since=now - 5.0, last_seen=now,
        summary=f"{rule} is firing", fired_at=now - 1.0,
    )


def _sup(tmp_path, name="state"):
    return Supervisor(state_dir=tmp_path / name, runner=FakeRunner())


def _armed(tmp_path, policy, name="serve", workers=0):
    sup = _sup(tmp_path)
    job = _rjob(name=name, policy=policy, workers=workers)
    sup.submit(job)
    key = f"default/{name}"
    return sup, key, sup.store.get(key)


# ---- policy API ----


class TestPolicyAPI:
    def test_roundtrip_and_presence_arms(self):
        pol = RemediationPolicy(
            dry_run=False, cooldown_s=7.0, backoff=3.0, max_actions=5,
            scale_min=2, scale_max=6, idle_s=12.0,
            routes=[
                RemediationRoute(rule="step_time_regression",
                                 webhook="http://hook.local/x"),
                RemediationRoute(rule="batch_size_collapse",
                                 exec=["/bin/true", "arg"]),
            ],
        )
        job = _rjob(policy=pol)
        back = job_from_dict(job.to_dict())
        rp = back.spec.remediation
        assert rp is not None and rp.dry_run is False
        assert rp.cooldown_s == 7.0 and rp.backoff == 3.0
        assert rp.scale_min == 2 and rp.scale_max == 6
        assert [r.rule for r in rp.routes] == [
            "step_time_regression", "batch_size_collapse",
        ]
        assert rp.routes[1].exec == ["/bin/true", "arg"]
        # Presence arms: an empty block round-trips as an armed policy
        # with the safe default (dry_run) — like `serving: {}`.
        d = job.to_dict()
        d["spec"]["remediation"] = {}
        armed = job_from_dict(d)
        assert armed.spec.remediation is not None
        assert armed.spec.remediation.dry_run is True
        # Absent stays absent.
        del d["spec"]["remediation"]
        assert job_from_dict(d).spec.remediation is None

    def test_validation_rejects_bad_policies(self):
        for pol, msg in [
            (RemediationPolicy(backoff=0.5), "backoff"),
            (RemediationPolicy(cooldown_s=-1.0), "cooldown_s"),
            (RemediationPolicy(scale_min=0), "scale_min"),
            (RemediationPolicy(scale_min=4, scale_max=2), "scale_max"),
            (RemediationPolicy(routes=[
                RemediationRoute(rule="bogus", webhook="http://x"),
            ]), "bogus"),
            (RemediationPolicy(routes=[
                RemediationRoute(rule="straggler", webhook="http://x",
                                 exec=["/bin/true"]),
            ]), "exactly one"),
            (RemediationPolicy(routes=[
                RemediationRoute(rule="straggler"),
            ]), "exactly one"),
        ]:
            with pytest.raises(Exception) as ei:
                validate(_rjob(policy=pol))
            assert msg in str(ei.value), f"{pol} -> {ei.value}"
        validate(_rjob(policy=RemediationPolicy(routes=[
            RemediationRoute(rule="step_time_regression", webhook="http://x"),
        ])))

    def test_policy_threads_into_env(self):
        from pytorch_operator_tpu.runtime.env import build_cluster_env

        job = _rjob(policy=RemediationPolicy(dry_run=False, scale_max=4))
        env = build_cluster_env(job, ReplicaType.MASTER, 0)
        threaded = json.loads(env["TPUJOB_REMEDIATION"])
        assert threaded["dry_run"] is False and threaded["scale_max"] == 4
        assert "TPUJOB_REMEDIATION" not in build_cluster_env(
            _rjob(policy=None), ReplicaType.MASTER, 0
        )
        # A committed cadence raise reaches the workload.
        job.metadata.annotations[CKPT_CADENCE_ANNOTATION] = "2"
        env = build_cluster_env(job, ReplicaType.MASTER, 0)
        assert env["TPUJOB_CKPT_CADENCE_FACTOR"] == "2"


# ---- engine units ----


class TestGrowShrink:
    def test_slo_burn_grows_fast_and_clamps(self, tmp_path):
        sup, key, job = _armed(
            tmp_path, RemediationPolicy(dry_run=False, scale_max=3)
        )
        rec = sup.remediation.evaluate(
            key, job, [_alert(key, "slo_burn")], now=T0
        )
        assert rec["action"] == "scale_up" and rec["outcome"] == "applied"
        assert rec["detail"] == {"from": 1, "to": 2}
        assert rec["generation"] == 1
        assert job.spec.total_replicas() == 2
        assert job.status.remediation_generation == 1
        # The annotation snapshot rides the same committed write.
        snap = json.loads(
            job.metadata.annotations[LAST_REMEDIATION_ANNOTATION]
        )
        assert snap["generation"] == 1 and snap["action"] == "scale_up"
        # Next grow (past cooldown) doubles toward the clamp.
        rec = sup.remediation.evaluate(
            key, job, [_alert(key, "queue_growth", severity="warning")],
            now=T0 + 100.0,
        )
        assert rec["detail"] == {"from": 2, "to": 3}
        # At the clamp the candidate is inapplicable: no action, no
        # generation burn.
        assert sup.remediation.evaluate(
            key, job, [_alert(key, "slo_burn")], now=T0 + 1000.0
        ) is None
        assert job.status.remediation_generation == 2
        assert sup.metrics.remediations_total.get(
            job=key, rule="slo_burn", action="scale_up", outcome="applied"
        ) == 1

    def test_sustained_idle_shrinks_slow(self, tmp_path):
        sup, key, job = _armed(
            tmp_path,
            RemediationPolicy(dry_run=False, idle_s=60.0, scale_min=1),
            workers=2,
        )
        idle = {"queue_depth": 0, "inflight": 0}
        # Idle starts the clock; nothing shrinks before idle_s.
        assert sup.remediation.evaluate(key, job, [], serve=idle, now=T0) is None
        assert sup.remediation.evaluate(
            key, job, [], serve=idle, now=T0 + 30.0
        ) is None
        rec = sup.remediation.evaluate(
            key, job, [], serve=idle, now=T0 + 61.0
        )
        assert rec["action"] == "scale_down"
        assert rec["rule"] == "sustained_idle"
        assert rec["detail"] == {"from": 3, "to": 2}  # ONE seat, not half
        # Busy (or firing) resets the idle watermark.
        assert sup.remediation.evaluate(
            key, job, [], serve={"queue_depth": 4, "inflight": 1},
            now=T0 + 200.0,
        ) is None
        assert sup.remediation.evaluate(
            key, job, [], serve=idle, now=T0 + 230.0
        ) is None  # only 30s idle again
        # A firing alert suppresses the shrink even when idle long.
        sup.remediation.evaluate(
            key, job, [_alert(key, "straggler", replica="worker-0")],
            serve=idle, now=T0 + 400.0,
        )
        assert sup.remediation.evaluate(
            key, job, [], serve=idle, now=T0 + 430.0
        ) is None

    def test_shrink_floors_at_scale_min(self, tmp_path):
        sup, key, job = _armed(
            tmp_path,
            RemediationPolicy(dry_run=False, idle_s=0.0, cooldown_s=0.0,
                              scale_min=2),
            workers=1,
        )
        idle = {"queue_depth": 0, "inflight": 0}
        sup.remediation.evaluate(key, job, [], serve=idle, now=T0)
        assert sup.remediation.evaluate(
            key, job, [], serve=idle, now=T0 + 1.0
        ) is None  # already at the floor (total 2)


class TestGates:
    def test_cooldown_and_backoff_hysteresis(self, tmp_path):
        sup, key, job = _armed(
            tmp_path,
            RemediationPolicy(dry_run=False, cooldown_s=10.0, backoff=2.0,
                              scale_max=8),
        )
        burn = lambda t: sup.remediation.evaluate(
            key, job, [_alert(key, "slo_burn")], now=t
        )
        assert burn(T0) is not None
        # Streak 1: next action needs cooldown_s.
        assert burn(T0 + 9.0) is None
        assert burn(T0 + 10.5) is not None
        # Streak 2: the window doubles (cooldown * backoff).
        assert burn(T0 + 10.5 + 15.0) is None
        assert burn(T0 + 10.5 + 21.0) is not None

    def test_budget_is_the_committed_generation(self, tmp_path):
        sup, key, job = _armed(
            tmp_path,
            RemediationPolicy(dry_run=False, cooldown_s=0.0, max_actions=2,
                              scale_max=8),
        )
        a = [_alert(key, "slo_burn")]
        assert sup.remediation.evaluate(key, job, a, now=T0) is not None
        assert sup.remediation.evaluate(key, job, a, now=T0 + 1) is not None
        assert sup.remediation.evaluate(key, job, a, now=T0 + 2) is None
        assert job.status.remediation_generation == 2
        assert "RemediationBudgetExhausted" in [
            e.reason for e in sup.events.for_job(key)
        ]

    def test_dry_run_audits_but_never_acts(self, tmp_path):
        sup, key, job = _armed(tmp_path, RemediationPolicy())  # safe default
        before = job.spec.to_dict()
        rec = sup.remediation.evaluate(
            key, job, [_alert(key, "slo_burn")], now=T0
        )
        assert rec["outcome"] == "dry_run"
        assert job.spec.to_dict() == before
        assert job.status.remediation_generation == 0
        assert LAST_REMEDIATION_ANNOTATION not in job.metadata.annotations
        assert sup.runner.actions == []
        recs = load_remediation_log(sup.state_dir, key)
        assert [r["outcome"] for r in recs] == ["dry_run"]
        assert recs[0]["alert"]["rule"] == "slo_burn"
        assert "RemediationDryRun" in [
            e.reason for e in sup.events.for_job(key)
        ]


class TestActuators:
    def test_preempt_resolves_replica_and_fires_post_commit(self, tmp_path):
        sup, key, job = _armed(
            tmp_path,
            RemediationPolicy(dry_run=False), name="train", workers=1,
        )
        sup.sync_once()  # spawn the fake replicas
        rec = sup.remediation.evaluate(
            key, job,
            [_alert(key, "heartbeat_silence", replica="worker-0")],
            now=T0,
        )
        assert rec["action"] == "preempt" and rec["outcome"] == "applied"
        assert rec["alert"]["replica"] == "worker-0"
        assert rec["fence"] is None or "token" in rec["fence"]
        victim = next(
            h for h in sup.runner.list_for_job(key)
            if h.name.endswith("worker-0")
        )
        assert not victim.is_active() and victim.exit_code == 143
        # Victim gone -> the candidate is inapplicable, not an error.
        for h in sup.runner.list_for_job(key):
            sup.runner.delete(h.name)
        assert sup.remediation.evaluate(
            key, job,
            [_alert(key, "straggler", replica="worker-0")],
            now=T0 + 100.0,
        ) is None

    def test_checkpoint_lag_raises_cadence_once(self, tmp_path):
        sup, key, job = _armed(
            tmp_path, RemediationPolicy(dry_run=False), name="ckpt",
        )
        rec = sup.remediation.evaluate(
            key, job, [_alert(key, "checkpoint_lag", severity="warning")],
            now=T0,
        )
        assert rec["action"] == "raise_ckpt_cadence"
        assert job.spec.data_plane.async_checkpoint is True
        assert job.metadata.annotations[CKPT_CADENCE_ANNOTATION] == "2"
        # Already raised: nothing left to turn up.
        assert sup.remediation.evaluate(
            key, job, [_alert(key, "checkpoint_lag", severity="warning")],
            now=T0 + 100.0,
        ) is None

    def test_exec_route_delivers_audit_record(self, tmp_path):
        out = tmp_path / "delivered.json"
        pol = RemediationPolicy(dry_run=False, routes=[
            RemediationRoute(rule="step_time_regression", exec=[
                sys.executable, "-c",
                "import sys, pathlib; pathlib.Path(sys.argv[1])"
                ".write_bytes(sys.stdin.buffer.read())",
                str(out),
            ]),
        ])
        sup, key, job = _armed(tmp_path, pol, name="routed")
        rec = sup.remediation.evaluate(
            key, job,
            [_alert(key, "step_time_regression", severity="warning")],
            now=T0,
        )
        assert rec["action"] == "route"
        delivered = json.loads(out.read_bytes())
        assert delivered["rule"] == "step_time_regression"
        assert delivered["generation"] == 1
        # A rule with neither builtin nor route is skipped entirely.
        assert sup.remediation.evaluate(
            key, job,
            [_alert(key, "world_resize_thrash", severity="warning")],
            now=T0 + 100.0,
        ) is None


# ---- exactly-once under failover ----


class TestExactlyOnceFailover:
    def test_commit_append_window_heals_without_reacting(
        self, tmp_path, monkeypatch
    ):
        """The dead supervisor committed (spec + generation + annotation
        in ONE store write) but died before the audit append. The
        adopter re-materialises the audit record from the annotation
        and stays inside the cooldown — the action happened ONCE."""
        sup1, key, job = _armed(
            tmp_path, RemediationPolicy(dry_run=False, cooldown_s=300.0)
        )
        monkeypatch.setattr(
            sup1.remediation, "_append", lambda *a, **k: None
        )
        rec = sup1.remediation.evaluate(
            key, job, [_alert(key, "slo_burn")], now=T0
        )
        assert rec["generation"] == 1
        assert load_remediation_log(sup1.state_dir, key) == []  # lost

        sup2 = Supervisor(state_dir=sup1.state_dir, runner=FakeRunner())
        job2 = sup2.store.get(key)
        assert job2.status.remediation_generation == 1  # commit survived
        again = sup2.remediation.evaluate(
            key, job2, [_alert(key, "slo_burn")], now=T0 + 1.0
        )
        assert again is None  # adopted cooldown gates the repeat
        recs = load_remediation_log(sup2.state_dir, key)
        assert [r["generation"] for r in recs] == [1]  # healed, once
        assert recs[0]["outcome"] == "applied"
        assert job2.spec.total_replicas() == 2
        assert job2.status.remediation_generation == 1
        assert "RemediationAdopted" in [
            e.reason for e in sup2.events.for_job(key)
        ]
        # A third sight heals nothing further (idempotent adoption).
        sup3 = Supervisor(state_dir=sup1.state_dir, runner=FakeRunner())
        sup3.remediation.evaluate(
            key, sup3.store.get(key), [_alert(key, "slo_burn")],
            now=T0 + 2.0,
        )
        assert len(load_remediation_log(sup3.state_dir, key)) == 1

    def test_scale_down_side_effect_is_healed_not_redecided(
        self, tmp_path, monkeypatch
    ):
        """Death in the append→side-effect window of a scale-down: the
        committed spec says 2 seats, 3 still run. Adoption re-runs the
        deterministic seat delete off the committed spec — it does NOT
        re-decide (no new generation, no new audit record)."""
        sup1, key, job = _armed(
            tmp_path,
            RemediationPolicy(dry_run=False, idle_s=10.0, scale_min=1),
            name="shrink", workers=2,
        )
        sup1.sync_once()
        assert len([h for h in sup1.runner.list_for_job(key)
                    if h.is_active()]) == 3
        monkeypatch.setattr(
            sup1.remediation, "_apply", lambda *a, **k: None
        )
        # sync_once ran the in-pass evaluate with the wall clock, so
        # stay on it: watermark now, shrink once sustained past idle_s.
        t = time.time()
        idle = {"queue_depth": 0, "inflight": 0}
        sup1.remediation.evaluate(key, job, [], serve=idle, now=t)
        rec = sup1.remediation.evaluate(
            key, job, [], serve=idle, now=t + 60.0
        )
        assert rec["action"] == "scale_down"
        # The doomed seat still runs: the effect was lost with the owner.
        assert len([h for h in sup1.runner.list_for_job(key)
                    if h.is_active()]) == 3

        sup2 = Supervisor(state_dir=sup1.state_dir, runner=sup1.runner)
        job2 = sup2.store.get(key)
        sup2.remediation.evaluate(key, job2, [], serve=idle, now=t + 61.0)
        assert len([h for h in sup2.runner.list_for_job(key)
                    if h.is_active()]) == 2
        assert job2.status.remediation_generation == 1  # healed, not redone
        assert len(load_remediation_log(sup2.state_dir, key)) == 1


# ---- e2e: preempt-into-restart beats the hang-deadline kill ----


@pytest.mark.chaos
def test_preempt_recycles_silent_replica_before_hang_kill(tmp_path):
    """A replica goes silent under a drop_heartbeat fault pinned to its
    first incarnation, with a hang deadline far beyond the test budget.
    The remediation preempt (SIGTERM, exit 143, retryable) recycles it
    through the ordinary restart path and the job FINISHES — strictly
    faster than the hang-deadline kill, which never fires."""
    faults.disarm()
    state = tmp_path / "state"
    sup = Supervisor(state_dir=state, poll_interval=0.03)
    key = "default/heal-e2e"
    try:
        faults.arm(FaultPlan(seed=1, faults=[
            Fault(kind="drop_heartbeat", target="master-0",
                  nth=3, times=100000, restart=0),
        ]))
        job = TPUJob(
            metadata=ObjectMeta(
                name="heal-e2e",
                annotations={HANG_DEADLINE_ANNOTATION: "120"},
            ),
            spec=TPUJobSpec(
                replica_specs={
                    ReplicaType.MASTER: ReplicaSpec(
                        replicas=1,
                        restart_policy=RestartPolicy.ON_FAILURE,
                        template=ProcessTemplate(
                            module="pytorch_operator_tpu.workloads.exit_with",
                            args=["--steps", "40", "--step-time", "0.05"],
                        ),
                    ),
                },
                run_policy=RunPolicy(),
                remediation=RemediationPolicy(
                    dry_run=False, cooldown_s=5.0
                ),
            ),
        )
        set_defaults(job)
        sup.submit(job)
        deadline = time.time() + 60.0
        j = None
        while time.time() < deadline:
            sup.sync_once()
            j = sup.store.get(key)
            if j is None or j.is_finished():
                sup.sync_once()
                break
            time.sleep(0.03)
        reasons = [e.reason for e in sup.events.for_job(key)]
    finally:
        faults.disarm()
        sup.shutdown()
    assert j is not None and j.is_succeeded(), reasons
    assert "RemediationApplied" in reasons
    assert "TPUJobHung" not in reasons
    recs = load_remediation_log(state, key)
    preempts = [r for r in recs if r["action"] == "preempt"]
    assert preempts and preempts[0]["outcome"] == "applied"
    assert preempts[0]["alert"]["rule"] == "heartbeat_silence"
    assert preempts[0]["generation"] >= 1
