"""Native prefetch loader + array file tests.

Reference analog: the reference's input pipeline is torch DataLoader's
native worker layer inside user containers (SURVEY.md §2 preamble); here
it's native/loader.cc + the ctypes binding, tested against the pure-numpy
fallback for identical contracts.
"""

from __future__ import annotations

import numpy as np
import pytest

from pytorch_operator_tpu.data import (
    LoaderDataError,
    LoaderUnavailable,
    open_loader,
    pack_arrays,
    read_meta,
)


@pytest.fixture
def packed(tmp_path):
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 4, 4, 3)).astype(np.float32)
    y = np.arange(64, dtype=np.int32)  # unique labels → order tracking
    path = tmp_path / "data.bin"
    meta = pack_arrays(path, {"x": x, "y": y})
    return path, meta, x, y


def _loader(path, native, **kw):
    try:
        return open_loader(path, native=native, **kw)
    except LoaderUnavailable as e:
        pytest.skip(f"native loader unavailable: {e}")


class TestArrayFile:
    def test_meta_roundtrip(self, packed):
        path, meta, x, y = packed
        m = read_meta(path)
        assert m.n_records == 64
        assert [f.name for f in m.fields] == ["x", "y"]
        assert m.fields[0].shape == (4, 4, 3)
        assert m.fields[0].dtype == "float32"
        assert m.record_bytes == 4 * 4 * 3 * 4 + 4

    def test_pack_rejects_ragged(self, tmp_path):
        with pytest.raises(ValueError, match="records"):
            pack_arrays(
                tmp_path / "bad.bin",
                {"x": np.zeros((4, 2)), "y": np.zeros(3)},
            )


@pytest.mark.parametrize("native", [True, False], ids=["native", "python"])
class TestLoaderContract:
    def test_ordered_batches_match_source(self, packed, native):
        path, meta, x, y = packed
        with _loader(path, native, batch=16, shuffle=False) as ld:
            assert ld.batches_per_epoch == 4
            for b in range(4):
                epoch, index, fields = ld.next_batch()
                assert (epoch, index) == (0, b)
                np.testing.assert_array_equal(fields["y"], y[b * 16 : (b + 1) * 16])
                np.testing.assert_array_equal(fields["x"], x[b * 16 : (b + 1) * 16])
            # Wraps into epoch 1, same order without shuffle.
            epoch, index, fields = ld.next_batch()
            assert (epoch, index) == (1, 0)
            np.testing.assert_array_equal(fields["y"], y[:16])

    def test_shuffle_epoch_covers_all_records_once(self, packed, native):
        path, meta, x, y = packed
        with _loader(path, native, batch=16, shuffle=True, seed=7) as ld:
            seen = []
            for _ in range(ld.batches_per_epoch):
                _, _, fields = ld.next_batch()
                seen.extend(fields["y"].tolist())
            assert sorted(seen) == list(range(64))  # exactly once each
            assert seen != list(range(64))  # actually shuffled

    def test_shuffle_reproducible_and_epoch_varying(self, packed, native):
        path, meta, x, y = packed

        def first_epoch(seed):
            with _loader(path, native, batch=16, shuffle=True, seed=seed) as ld:
                out = []
                for _ in range(ld.batches_per_epoch):
                    out.extend(ld.next_batch()[2]["y"].tolist())
                return out

        assert first_epoch(3) == first_epoch(3)
        assert first_epoch(3) != first_epoch(4)

        with _loader(path, native, batch=16, shuffle=True, seed=3) as ld:
            e0, e1 = [], []
            for _ in range(ld.batches_per_epoch):
                e0.extend(ld.next_batch()[2]["y"].tolist())
            for _ in range(ld.batches_per_epoch):
                e1.extend(ld.next_batch()[2]["y"].tolist())
            assert sorted(e0) == sorted(e1)
            assert e0 != e1  # fresh permutation per epoch

    def test_records_intact_under_shuffle(self, packed, native):
        """x rows must travel with their y labels through the gather."""
        path, meta, x, y = packed
        with _loader(path, native, batch=16, shuffle=True, seed=1) as ld:
            _, _, fields = ld.next_batch()
            for row, label in zip(fields["x"], fields["y"]):
                np.testing.assert_array_equal(row, x[label])


class TestNativeSpecifics:
    def test_open_rejects_short_file(self, tmp_path, packed):
        path, meta, x, y = packed
        short = tmp_path / "short.bin"
        short.write_bytes(path.read_bytes()[: meta.record_bytes * 10])
        try:
            from pytorch_operator_tpu.data.native_loader import NativeLoader

            with pytest.raises(LoaderDataError, match="open failed"):
                NativeLoader(short, batch=16, meta=meta)
        except LoaderUnavailable as e:
            pytest.skip(f"native loader unavailable: {e}")

    def test_batch_larger_than_dataset_rejected(self, packed):
        path, meta, x, y = packed
        from pytorch_operator_tpu.data.native_loader import NativeLoader, _load_lib

        try:
            _load_lib()
        except LoaderUnavailable as e:
            pytest.skip(f"native loader unavailable: {e}")
        with pytest.raises(LoaderDataError, match="open failed"):
            NativeLoader(path, batch=128)

    def test_stashed_batches_keep_image_label_pairing(self, packed):
        """The resnet --data-file path stashes ``chunk`` batches before
        stacking. Slots are reused after ``prefetch`` calls, so stashing
        works ONLY with copies (x via astype, y via .copy()) — this guards
        that idiom against silent image/label mismatch."""
        path, meta, x, y = packed
        ld = _loader(path, True, batch=8, shuffle=True, seed=2, prefetch=3)
        try:
            xs, ys = [], []
            for _ in range(6):  # > prefetch: slots recycle under our feet
                _, _, fields = ld.next_batch()
                xs.append(fields["x"].astype(np.float32))
                ys.append(fields["y"].copy())
            for bx, by in zip(np.stack(xs), np.stack(ys)):
                for row, label in zip(bx, by):
                    np.testing.assert_array_equal(row, x[label])
        finally:
            ld.close()

    def test_prefetch_overlaps(self, packed):
        """The producer fills the ring while the consumer is idle."""
        import time

        path, meta, x, y = packed
        ld = _loader(path, True, batch=16, prefetch=3)
        try:
            time.sleep(0.3)  # producer should have filled the ring by now
            t0 = time.time()
            ld.next_batch()
            assert time.time() - t0 < 0.1  # served from the ring, no wait
        finally:
            ld.close()


class TestMnistIntegration:
    def test_mnist_trains_from_data_file(self, tmp_path):
        import tests.jaxenv  # noqa: F401

        from pytorch_operator_tpu.data.pack import main as pack_main
        from pytorch_operator_tpu.workloads.mnist_train import main as mnist_main

        out = tmp_path / "digits.bin"
        assert pack_main(["--out", str(out), "--dataset", "digits"]) == 0
        rc = mnist_main(
            [
                "--epochs",
                "4",
                "--batch-size",
                "128",
                "--target-acc",
                "0.9",
                "--data-file",
                str(out),
            ]
        )
        assert rc == 0
