"""Monitoring endpoint + leader election tests.

Reference analogs: promhttp on ``--monitoring-port`` and
``leaderelection.RunOrDie`` (SURVEY.md §2 "Metrics", "Entrypoint/CLI").
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
import urllib.error
import urllib.request
from pathlib import Path

from pytorch_operator_tpu.controller.leases import LeaderLease
from pytorch_operator_tpu.controller.monitoring import (
    MonitoringServer,
    supervisor_health,
)
from pytorch_operator_tpu.controller.supervisor import Supervisor

from tests.testutil import new_job


def _get(port: int, path: str):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}", timeout=5) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


class TestMonitoringServer:
    def test_serves_metrics_and_healthz(self, tmp_path):
        sup = Supervisor(state_dir=tmp_path, persist=False)
        srv = MonitoringServer(
            render_metrics=sup.metrics.render_text,
            health=lambda: supervisor_health(sup),
            port=0,
        )
        port = srv.start()
        try:
            sup.run(new_job(name="mon-ok", workers=0), timeout=60)

            status, ctype, body = _get(port, "/metrics")
            assert status == 200
            assert ctype.startswith("text/plain")
            assert "tpujob_jobs_created_total 1" in body
            assert "tpujob_jobs_succeeded_total 1" in body

            status, ctype, body = _get(port, "/healthz")
            assert status == 200
            assert ctype == "application/json"
            doc = json.loads(body)
            assert doc["status"] == "ok"
            assert doc["jobs"] == {"Succeeded": 1}
            # No lease configured → no leader fields.
            assert "leader" not in doc
        finally:
            srv.stop()
            sup.shutdown()

    def test_scheduler_gauges_reflect_pass_state(self, tmp_path):
        """Gauges (active jobs/replicas, slot usage, queue usage, held
        gangs) refresh every supervisor pass."""
        from pytorch_operator_tpu.controller.runner import FakeRunner

        sup = Supervisor(
            state_dir=None,
            runner=FakeRunner(capacity=3),
            persist=False,
            queue_slots={"q": 2},
        )
        a = new_job(name="a", workers=1)  # 2 replicas
        a.spec.run_policy.scheduling_policy.queue = "q"
        big = new_job(name="big", workers=4)  # gang of 5 > 3 → held
        sup.submit(a)
        sup.submit(big)
        sup.sync_once()
        m = sup.metrics
        assert m.jobs_active.get() == 2
        assert m.replicas_active.get() == 2
        assert m.slots_used.get() == 2
        assert m.slots_capacity.get() == 3
        assert m.gangs_held.get() == 1
        assert m.queue_slots_used.get(queue="q") == 2
        assert m.queue_slots_capacity.get(queue="q") == 2
        text = m.render_text()
        assert 'tpujob_queue_slots_used{queue="q"} 2' in text
        assert "tpujob_gangs_held 1" in text

    def test_progress_gauges_fold_workload_heartbeats(self, tmp_path):
        """SURVEY §5 requires steps/sec + images/sec/chip meters ON the
        operator surface (VERDICT r2 Missing #1): the supervisor tails
        each running job's newest progress heartbeat into per-job
        gauges every pass, and clears them when the job finishes."""
        import json

        from pytorch_operator_tpu.api.types import ReplicaPhase, ReplicaType
        from pytorch_operator_tpu.controller.runner import FakeRunner, replica_name
        from pytorch_operator_tpu.controller.store import key_to_fs

        sup = Supervisor(
            state_dir=tmp_path, runner=FakeRunner(), persist=False
        )
        key = sup.submit(new_job(name="meter", workers=0))
        sup.sync_once()
        # The workload heartbeats (two records; the newer must win).
        sdir = tmp_path / "status" / key_to_fs(key)
        sdir.mkdir(parents=True, exist_ok=True)
        (sdir / "master-0.jsonl").write_text(
            json.dumps({"event": "progress", "ts": 100.0, "step": 10,
                        "loss": 2.5, "steps_per_sec": 4.0,
                        "throughput": 512.0, "unit": "images/sec/chip"})
            + "\n"
            + json.dumps({"event": "progress", "ts": 101.0, "step": 20,
                          "loss": 2.25, "steps_per_sec": 5.0,
                          "throughput": 640.0, "unit": "images/sec/chip"})
            + "\n"
        )
        sup.sync_once()
        m = sup.metrics
        assert m.job_step.get(job=key) == 20
        assert m.job_steps_per_sec.get(job=key) == 5.0
        assert m.job_throughput.get(job=key, unit="images/sec/chip") == 640.0
        assert m.job_loss.get(job=key) == 2.25
        # The staleness signal: ts=101.0 is epoch-ancient, so age is huge
        # — a hung job's healthy-looking rate is distinguishable.
        assert m.job_progress_age.get(job=key) > 3600
        text = m.render_text()
        assert (
            'tpujob_job_throughput{job="default/meter",unit="images/sec/chip"} 640'
            in text
        )
        assert 'tpujob_job_steps_per_sec{job="default/meter"} 5' in text
        # Finished jobs must not linger as stale series.
        sup.runner.set_phase(
            replica_name(key, ReplicaType.MASTER, 0), ReplicaPhase.SUCCEEDED
        )
        sup.sync_once()
        assert m.job_steps_per_sec.get(job=key) == 0.0
        sup.shutdown()

    def test_label_values_escaped(self):
        from pytorch_operator_tpu.controller.metrics import Gauge

        g = Gauge("g")
        g.set(1, queue='we"ird\\q\nx')
        rendered = g.render()
        assert 'queue="we\\"ird\\\\q\\nx"' in rendered

    def test_extra_text_routes_served(self, tmp_path):
        """The daemon mounts `tpujob top`'s table at /top via
        text_routes — same plaintext contract as /metrics."""
        from pytorch_operator_tpu.obs import top as obs_top

        sup = Supervisor(state_dir=tmp_path / "state")
        srv = MonitoringServer(
            render_metrics=sup.metrics.render_text,
            health=lambda: supervisor_health(sup),
            text_routes={"/top": lambda: obs_top.render(sup.state_dir) + "\n"},
        )
        port = srv.start()
        try:
            status, ctype, body = _get(port, "/top")
            assert status == 200
            assert ctype.startswith("text/plain")
            assert "CKPT LAG" in body
        finally:
            srv.stop()
            sup.shutdown()

    def test_unknown_path_404(self, tmp_path):
        sup = Supervisor(state_dir=tmp_path, persist=False)
        srv = MonitoringServer(
            render_metrics=sup.metrics.render_text,
            health=lambda: supervisor_health(sup),
        )
        port = srv.start()
        try:
            try:
                _get(port, "/nope")
                raise AssertionError("expected HTTP 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
        finally:
            srv.stop()
            sup.shutdown()

    def test_healthz_reports_leader(self, tmp_path):
        sup = Supervisor(state_dir=tmp_path, persist=False, leader_elect=True)
        assert sup.lease.acquire(blocking=False)
        doc = supervisor_health(sup)
        assert doc["is_leader"] is True
        assert doc["leader"] == sup.lease.identity
        sup.shutdown()


class TestLeaderLease:
    def test_exclusive_between_fds(self, tmp_path):
        a = LeaderLease(tmp_path, identity="a")
        b = LeaderLease(tmp_path, identity="b")
        assert a.acquire(blocking=False)
        # flock locks attach to the open file description, so a second
        # open() conflicts even within one process.
        assert not b.acquire(blocking=False)
        assert b.holder() == "a"
        a.release()
        assert b.acquire(blocking=False)
        assert a.holder() == "b"
        b.release()
        assert a.holder() is None

    def test_reacquire_is_noop(self, tmp_path):
        a = LeaderLease(tmp_path, identity="a")
        assert a.acquire()
        assert a.acquire(blocking=False)
        a.release()

    def test_blocking_acquire_times_out(self, tmp_path):
        a = LeaderLease(tmp_path, identity="a")
        b = LeaderLease(tmp_path, identity="b")
        a.acquire()
        t0 = time.time()
        assert not b.acquire(timeout=0.3)
        assert time.time() - t0 >= 0.3
        a.release()

    def test_holder_is_lock_free_and_detects_stale_records(self, tmp_path):
        """holder() must not touch the flock (a probe would contend with
        a real election) — it reads the record and judges liveness by
        pid, so a crashed leader's stale record reads as None."""
        import json as _json

        lease = LeaderLease(tmp_path, identity="obs")
        (tmp_path / "leader.lock").write_text(
            _json.dumps({"holder": "ghost", "pid": 99_999_999})
        )
        assert lease.holder() is None  # dead pid ⇒ crash-released
        (tmp_path / "leader.lock").write_text(
            _json.dumps({"holder": "me", "pid": __import__("os").getpid()})
        )
        assert lease.holder() == "me"  # live pid ⇒ trusted record
        (tmp_path / "leader.lock").write_text("not json")
        assert lease.holder() == "<unknown>"

    def test_crash_releases_lease(self, tmp_path):
        """OS-level release on holder death — the fail-over property."""
        repo_root = str(Path(__file__).resolve().parents[1])
        holder = subprocess.Popen(
            [
                sys.executable,
                "-c",
                "import sys, time; sys.path.insert(0, %r); "
                "from pytorch_operator_tpu.controller.leases import LeaderLease; "
                "l = LeaderLease(%r, identity='crashy'); l.acquire(); "
                "print('held', flush=True); time.sleep(60)"
                % (repo_root, str(tmp_path)),
            ],
            stdout=subprocess.PIPE,
            text=True,
        )
        try:
            assert holder.stdout.readline().strip() == "held"
            standby = LeaderLease(tmp_path, identity="standby")
            assert not standby.acquire(blocking=False)
            holder.kill()
            holder.wait(timeout=10)
            assert standby.acquire(timeout=5)
            standby.release()
        finally:
            if holder.poll() is None:
                holder.kill()
